"""Perf-observatory tests (obs/perf.py + scripts/perf_gate.py):
BenchResult schema round-trip, structural-fingerprint determinism, the
two gate modes (structural fires on injected recompiles / FLOP growth
with the offending program named; timing is silent across identical
reruns but fires on an injected 1.5x slowdown), the trajectory store +
BENCH_r01-r05 backfill, the bench runner end-to-end, and the
summarize_metrics --compare view the gate's diagnosis reuses."""

import io
import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest

from building_llm_from_scratch_tpu.obs import CompileWatcher
from building_llm_from_scratch_tpu.obs import perf

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO_ROOT, "scripts")


def _capture_fingerprint(fn, *args, label="prog"):
    """Compile ``fn`` for ``args`` under a fresh CompileWatcher inside a
    fresh collector; returns the fingerprint."""
    watcher = CompileWatcher(jax.jit(fn), label=label)
    with perf.FingerprintCollector() as col:
        watcher(*args)
    return col.fingerprint()


# ---------------------------------------------------------------------------
# BenchResult schema
# ---------------------------------------------------------------------------

def test_bench_result_roundtrip():
    res = perf.BenchResult(name="toy", metric="toy tokens/sec", value=123.4,
                           unit="tokens/sec", detail={"arm": {"x": 1}},
                           vs_baseline=1.5, time=1700000000.0)
    res.add_metric("mfu", 0.41, "fraction")
    res.repeats = perf.repeat_stats([120.0, 123.4, 125.0])
    res.env = perf.bench_env()
    row = json.loads(json.dumps(res.to_row()))
    assert perf.validate_row(row) == []
    back = perf.BenchResult.from_row(row)
    assert back.name == "toy" and back.value == 123.4
    assert back.metric_value("mfu") == 0.41
    assert back.repeats["n"] == 3
    assert back.env["jax_version"] == jax.__version__
    # the env block carries what the ISSUE demands of a comparable number
    for key in ("backend", "device_kind", "device_count", "argv", "mesh"):
        assert key in back.env, key


def test_validate_row_rejects_malformed():
    assert perf.validate_row({"type": "bench"})  # missing everything
    good = perf.BenchResult(name="t", metric="m", value=1.0).to_row()
    bad = dict(good, value="fast")
    assert any("value" in p for p in perf.validate_row(bad))
    bad = dict(good, metrics={"mfu": 0.4})        # not {value, unit}
    assert any("metrics" in p for p in perf.validate_row(bad))
    newer = dict(good, perf_schema_version=perf.PERF_SCHEMA_VERSION + 1)
    assert any("newer" in p for p in perf.validate_row(newer))
    with pytest.raises(ValueError):
        perf.BenchResult.from_row({"type": "bench"})


def test_repeat_stats_math():
    st = perf.repeat_stats([10.0, 30.0, 20.0])
    assert st["n"] == 3 and st["min"] == 10.0 and st["median"] == 20.0
    assert st["mean"] == 20.0 and st["stddev"] == 10.0
    assert perf.repeat_stats([5.0])["stddev"] == 0.0


def test_bench_result_event_is_schema_registered():
    from building_llm_from_scratch_tpu.obs.schema import validate_event

    assert validate_event("bench_result", {
        "name": "micro_train", "metric": "m", "value": 1.0,
        "unit": "tokens/sec", "n_repeats": 2, "quick": True,
        "fingerprint_sha": "ab" * 32}) == []


# ---------------------------------------------------------------------------
# Structural fingerprints
# ---------------------------------------------------------------------------

def test_fingerprint_byte_identical_across_identical_runs():
    x = jnp.ones((32, 32), jnp.float32)
    fp1 = _capture_fingerprint(lambda a: (a @ a).sum(), x)
    fp2 = _capture_fingerprint(lambda a: (a @ a).sum(), x)
    blob1 = json.dumps(perf.structural_part(fp1), sort_keys=True)
    blob2 = json.dumps(perf.structural_part(fp2), sort_keys=True)
    assert blob1 == blob2
    assert perf.fingerprint_digest(fp1) == perf.fingerprint_digest(fp2)
    assert fp1["n_programs"] == 1 and fp1["n_recompiles"] == 0
    prog = fp1["programs"][0]
    assert prog["label"] == "prog" and prog["flops"] > 0
    assert perf.compare_structural(fp1, fp2) == []


def test_structural_gate_fires_on_forced_recompile():
    """An injected recompile (second arg signature after the legitimate
    one) must fail the structural gate, not just log."""
    base = _capture_fingerprint(lambda a: (a @ a).sum(),
                                jnp.ones((32, 32), jnp.float32))
    watcher = CompileWatcher(jax.jit(lambda a: (a @ a).sum()), label="prog")
    with perf.FingerprintCollector() as col:
        watcher(jnp.ones((32, 32), jnp.float32))
        watcher(jnp.ones((16, 16), jnp.float32))   # forced recompile
    fresh = col.fingerprint()
    assert fresh["n_recompiles"] == 1
    findings = perf.compare_structural(base, fresh)
    kinds = {f["kind"] for f in findings}
    assert "recompiles" in kinds and "program_count" in kinds
    rec = next(f for f in findings if f["kind"] == "recompiles")
    assert "prog" in rec["detail"]           # the offending program named


def test_structural_gate_fires_on_flop_increase():
    """Same arg signature, more FLOPs (an extra matmul slipped into the
    step): the finding names the program and carries the delta."""
    x = jnp.ones((32, 32), jnp.float32)
    base = _capture_fingerprint(lambda a: (a @ a).sum(), x)
    fresh = _capture_fingerprint(lambda a: (a @ a @ a).sum(), x)
    findings = perf.compare_structural(base, fresh)
    flops = [f for f in findings if f["kind"] == "flops_delta"]
    assert flops, findings
    assert flops[0]["program"] == "prog"
    assert flops[0]["fresh"] > flops[0]["base"]
    assert "prog" in flops[0]["detail"]
    # and the clean direction still holds
    assert perf.compare_structural(base, base) == []


def test_structural_gate_reports_new_and_removed_programs():
    x = jnp.ones((8, 8), jnp.float32)
    one = _capture_fingerprint(lambda a: (a @ a).sum(), x, label="p1")
    watcher1 = CompileWatcher(jax.jit(lambda a: (a @ a).sum()), label="p1")
    watcher2 = CompileWatcher(jax.jit(lambda a: a.sum()), label="p2")
    with perf.FingerprintCollector() as col:
        watcher1(x)
        watcher2(x)
    both = col.fingerprint()
    kinds = {f["kind"]: f for f in perf.compare_structural(one, both)}
    assert "new_program" in kinds and kinds["new_program"]["program"] == "p2"
    kinds_rev = {f["kind"]: f
                 for f in perf.compare_structural(both, one)}
    assert kinds_rev["removed_program"]["program"] == "p2"


def test_bucket_leak_names_the_stray_variant():
    """A label that GROWS a signature variant while keeping the baselined
    ones (the prefill bucket-leak scenario) must name the stray variant,
    not collapse it into a bare program-count delta."""
    x8 = jnp.ones((8, 8), jnp.float32)
    x16 = jnp.ones((16, 16), jnp.float32)
    base = _capture_fingerprint(lambda a: (a @ a).sum(), x8,
                                label="prefill")
    watcher = CompileWatcher(jax.jit(lambda a: (a @ a).sum()),
                             label="prefill", multi_program=True)
    with perf.FingerprintCollector() as col:
        watcher(x8)
        watcher(x16)            # the leaked bucket
    fresh = col.fingerprint()
    findings = perf.compare_structural(base, fresh)
    leak = [f for f in findings if f["kind"] == "new_program_variant"]
    assert len(leak) == 1 and leak[0]["program"] == "prefill"
    assert "prefill" in leak[0]["detail"]
    # and the reverse direction: the lost variant is named too
    rev = perf.compare_structural(fresh, base)
    gone = [f for f in rev if f["kind"] == "removed_program_variant"]
    assert len(gone) == 1 and gone[0]["program"] == "prefill"


def test_signature_change_pairs_programs_and_reports_flops():
    x32 = jnp.ones((32, 32), jnp.float32)
    x64 = jnp.ones((64, 64), jnp.float32)
    base = _capture_fingerprint(lambda a: (a @ a).sum(), x32)
    fresh = _capture_fingerprint(lambda a: (a @ a).sum(), x64)
    findings = perf.compare_structural(base, fresh)
    sig = [f for f in findings if f["kind"] == "arg_signature_changed"]
    assert len(sig) == 1 and sig[0]["program"] == "prog"
    assert "flops" in sig[0]["detail"]       # the delta rides along


# ---------------------------------------------------------------------------
# Timing mode
# ---------------------------------------------------------------------------

def _timing_row(values):
    row = perf.BenchResult(name="t", metric="m",
                           value=values[-1], unit="tok/s").to_row()
    row["repeats"] = perf.repeat_stats(values)
    return row


def test_timing_gate_silent_across_identical_reruns():
    base = _timing_row([100.0, 101.0, 99.5])
    for _ in range(5):                       # k identical reruns: no fire
        fresh = _timing_row([100.2, 99.8, 100.9])
        assert perf.compare_timing(base, fresh) is None


def test_timing_gate_fires_on_injected_slowdown():
    base = _timing_row([100.0, 101.0, 99.5])
    slow = _timing_row([66.0, 67.0, 66.5])   # 1.5x slowdown
    finding = perf.compare_timing(base, slow)
    assert finding is not None
    assert finding["kind"] == "timing_regression"
    assert finding["ratio"] < 0.7
    assert "noise floor" in finding["detail"]
    # faster is never a regression
    fast = _timing_row([150.0, 151.0, 149.0])
    assert perf.compare_timing(base, fast) is None


def test_timing_noise_floor_scales_with_stddev():
    noisy_base = _timing_row([100.0, 140.0, 60.0])   # huge variance
    dip = _timing_row([80.0, 82.0, 81.0])
    # a 20% dip inside 4 sigma of a 40-stddev baseline must NOT fire
    assert perf.compare_timing(noisy_base, dip) is None


# ---------------------------------------------------------------------------
# Trajectory store + BENCH_r01-r05 backfill
# ---------------------------------------------------------------------------

def test_trajectory_store_roundtrip(tmp_path):
    store = perf.TrajectoryStore(str(tmp_path / "perf"))
    res = perf.BenchResult(name="toy", metric="m", value=10.0,
                           time=1700000000.0)
    store.append(res)
    store.append(perf.BenchResult(name="toy", metric="m", value=12.0,
                                  time=1700000100.0))
    rows = store.load("toy")
    assert [r["value"] for r in rows] == [10.0, 12.0]
    assert store.names() == ["toy"]
    with pytest.raises(ValueError):
        store.append({"type": "bench", "name": "toy"})  # invalid row


def test_backfill_covers_bench_r01_to_r05(tmp_path):
    store = perf.TrajectoryStore(str(tmp_path / "perf"))
    added = perf.backfill_bench_history(REPO_ROOT, store)
    assert added == 5
    rows = store.load("headline")
    sources = sorted(r["source"] for r in rows)
    assert sources == [f"BENCH_r0{i}.json" for i in range(1, 6)]
    values = {r["source"]: r["value"] for r in rows}
    assert values["BENCH_r02.json"] == 37039.6
    assert values["BENCH_r05.json"] == 99274.1
    # r04/r05 carry MFU; every row validates against the schema
    assert all(perf.validate_row(r) == [] for r in rows)
    r05 = next(r for r in rows if r["source"] == "BENCH_r05.json")
    assert r05["metrics"]["mfu"]["value"] == 0.402
    # idempotent: a second backfill adds nothing
    assert perf.backfill_bench_history(REPO_ROOT, store) == 0
    out = io.StringIO()
    n = perf.render_trajectory(store, out=out)
    text = out.getvalue()
    assert n == 5
    for needle in ("BENCH_r01.json", "BENCH_r05.json", "99274.1", "0.402"):
        assert needle in text, text


def test_trajectory_tolerates_header_rows(tmp_path):
    """A trajectory file created via ``bench.py --json <file>.jsonl``
    starts with a header row; load() filters it and the report renders
    the bench rows instead of KeyErroring on the header."""
    store = perf.TrajectoryStore(str(tmp_path))
    os.makedirs(store.root, exist_ok=True)
    with open(store.path("toy"), "w") as f:
        f.write(json.dumps(perf.header_row()) + "\n")
        f.write(json.dumps(perf.BenchResult(
            name="toy", metric="m", value=5.0,
            time=1700000000.0).to_row()) + "\n")
    rows = store.load("toy")
    assert len(rows) == 1 and rows[0]["value"] == 5.0
    out = io.StringIO()
    assert perf.render_trajectory(store, out=out) == 1


def test_compare_structural_finding_iff_digest_differs():
    """The exact-match contract: zero findings iff the structural digests
    are equal — including the recompile-labels-drift edge where the
    counts match but the victims differ."""
    base = {"programs": [], "n_programs": 0, "n_recompiles": 1,
            "recompile_labels": ["decode"]}
    fresh = {"programs": [], "n_programs": 0, "n_recompiles": 1,
             "recompile_labels": ["prefill"]}
    assert perf.fingerprint_digest(base) != perf.fingerprint_digest(fresh)
    findings = perf.compare_structural(base, fresh)
    assert findings and any("decode" in f["detail"] for f in findings)
    assert perf.compare_structural(base, dict(base)) == []


def test_checked_in_trajectory_covers_history():
    """The committed results/perf/headline.jsonl must already contain the
    backfilled r01-r05 rows — the bench history is machine-readable in
    the repo itself, not only after running a script."""
    store = perf.TrajectoryStore()
    rows = store.load("headline")
    sources = {r.get("source") for r in rows}
    assert {f"BENCH_r0{i}.json" for i in range(1, 6)} <= sources


# ---------------------------------------------------------------------------
# Bench runner end-to-end (micro bench on the debug model)
# ---------------------------------------------------------------------------

def test_run_bench_micro_train_schema_and_fingerprint():
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)
    res = bench.run_bench("micro_train", repeats=2, quick=True)
    row = json.loads(json.dumps(res.to_row()))
    assert perf.validate_row(row) == []
    assert row["repeats"]["n"] == 2 and len(row["repeats"]["values"]) == 2
    assert row["env"]["jax_version"] == jax.__version__
    assert row["env"]["backend"] == "cpu"
    assert row["quick"] is True
    fp = row["fingerprint"]
    progs = [p for p in fp["programs"] if p["label"] == "bench_step"]
    assert progs and progs[0]["flops"] > 0
    assert fp["n_recompiles"] == 0
    assert fp["stable_across_repeats"] is True


def test_json_out_extensionless_path_is_a_directory(tmp_path):
    """``--json results/perf`` (no trailing slash, dir absent) must get
    the trajectory layout, not a FILE named like the trajectory dir."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.remove(REPO_ROOT)
    target = str(tmp_path / "results" / "perf")      # extensionless
    f = bench._open_json_out(target, "toy")
    f.close()
    assert os.path.isdir(target)
    assert os.path.exists(os.path.join(target, "toy.jsonl"))
    file_target = str(tmp_path / "out.jsonl")        # explicit file
    f = bench._open_json_out(file_target, "toy")
    f.close()
    assert os.path.isfile(file_target)
    rows = [json.loads(line) for line in open(file_target)]
    assert rows and rows[0]["type"] == "header"


def test_perf_report_path_is_jax_free(tmp_path):
    """perf_gate --report/--backfill must run without importing jax (the
    stdlib-only promise obs/perf.py makes for the pure-compare paths)."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.argv = ['perf_gate.py', '--report']; "
         f"sys.path.insert(0, {SCRIPTS!r}); import perf_gate; "
         "perf_gate.main(['--report']); "
         "assert 'jax' not in sys.modules, 'jax imported'"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stderr
    assert "perf trajectory" in proc.stdout


# ---------------------------------------------------------------------------
# The gate script itself (API-level, tmp baseline)
# ---------------------------------------------------------------------------

@pytest.fixture()
def perf_gate():
    sys.path.insert(0, SCRIPTS)
    sys.path.insert(0, REPO_ROOT)
    try:
        import perf_gate as pg
        yield pg
    finally:
        sys.path.remove(SCRIPTS)
        sys.path.remove(REPO_ROOT)


def test_perf_gate_end_to_end(perf_gate, tmp_path, monkeypatch, capsys):
    """--update-baseline (with a reason) -> structural gate passes; an
    injected per-program FLOP drift in the baseline -> rc 1 with the
    program named; --update-baseline without a reason refuses."""
    baseline = str(tmp_path / "PERF_BASELINE.json")
    monkeypatch.setattr(perf_gate, "BASELINE_JSONL_DIR",
                        str(tmp_path / "baseline_jsonl"))
    # no reason -> refusal before any bench runs
    assert perf_gate.main(["--update-baseline", "--baseline", baseline,
                           "--benches", "micro_train"]) == 2
    assert perf_gate.main(["--update-baseline", "--baseline", baseline,
                           "--benches", "micro_train",
                           "--reason", "test baseline"]) == 0
    data = json.load(open(baseline))
    assert data["updates"][-1]["reason"] == "test baseline"
    assert "micro_train" in data["benches"]
    assert data["benches"]["micro_train"]["fingerprint"]["programs"]
    # identical code -> structural gate green
    assert perf_gate.main(["--baseline", baseline]) == 0
    out = capsys.readouterr().out
    assert "perf gate ok: micro_train" in out
    # injected FLOP regression in the baseline -> gate fires, names it
    data["benches"]["micro_train"]["fingerprint"]["programs"][0][
        "flops"] *= 2
    with open(baseline, "w") as f:
        json.dump(data, f)
    assert perf_gate.main(["--baseline", baseline]) == 1
    out = capsys.readouterr().out
    assert "flops_delta" in out and "bench_step" in out
    # unknown bench name -> explicit refusal, not a KeyError
    assert perf_gate.main(["--baseline", baseline,
                           "--benches", "nope"]) == 2
    # a baseline entry whose bench no longer exists in bench.BENCHES
    # (renamed without re-baselining) -> clean rc-2 refusal, no KeyError
    data["benches"]["renamed_away"] = data["benches"].pop("micro_train")
    with open(baseline, "w") as f:
        json.dump(data, f)
    assert perf_gate.main(["--baseline", baseline]) == 2
    out = capsys.readouterr().out
    assert "renamed_away" in out and "re" in out.lower()


# ---------------------------------------------------------------------------
# summarize_metrics --compare (the gate's telemetry-diff view)
# ---------------------------------------------------------------------------

def test_compare_runs_on_fixture(capsys):
    sys.path.insert(0, SCRIPTS)
    try:
        import summarize_metrics
    finally:
        sys.path.remove(SCRIPTS)
    fixture = os.path.join(REPO_ROOT, "tests", "fixtures",
                           "metrics_fixture.jsonl")
    result = summarize_metrics.compare_runs(fixture, fixture)
    out = capsys.readouterr().out
    assert "A/B compare" in out
    assert "train step segments" in out
    # identical files -> identical stats, zero deltas
    a, b = result["a"], result["b"]
    assert a["train_segments_s_per_step"] == b["train_segments_s_per_step"]
    assert "+0.0%" in out
