"""Worker for the two-process jax.distributed smoke test (spawned by
tests/test_multiprocess.py — not collected by pytest).

Covers the genuinely multi-host code paths the in-process suite cannot:
``initialize_distributed`` explicit wiring, ``shard_batch``'s
``make_array_from_process_local_data`` branch, ``gather_full``'s
``process_allgather`` branch, and the checkpoint save/load leaf-at-a-time
collective ordering.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    pid, nproc, port, ckdir = (int(sys.argv[1]), int(sys.argv[2]),
                               sys.argv[3], sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else "fsdp"
    from building_llm_from_scratch_tpu.parallel import (
        build_mesh_plan,
        gather_full,
        initialize_distributed,
        sync_global_devices,
    )

    initialize_distributed(coordinator_address=f"localhost:{port}",
                           num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 4 * nproc, jax.device_count()

    from building_llm_from_scratch_tpu.configs import get_config
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        load_checkpoint,
        make_train_step,
        save_checkpoint,
    )

    cfg = get_config("GPT2", "124M", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=256, drop_rate=0.0)

    if mode == "pp":
        _run_pp(pid, nproc, cfg)
        return

    plan = build_mesh_plan(mode)
    params = init_params(cfg, jax.random.PRNGKey(0))   # same on all procs
    opt = build_optimizer(total_steps=10)
    state = plan.shard_state(
        init_train_state(params, opt, jax.random.PRNGKey(0)))
    if mode == "fsdp":
        wq = state["trainable"]["blocks"]["attn"]["wq"]
        assert not wq.is_fully_addressable        # really spans all hosts
    else:                                         # zero1: only opt state
        mu = jax.tree_util.tree_leaves(state["opt_state"])
        assert any(getattr(x, "is_fully_addressable", True) is False
                   for x in mu if hasattr(x, "sharding"))
    step = make_train_step(cfg, opt)

    rng = np.random.default_rng(0)
    losses = []
    for i in range(3):
        # per-process local rows; shard_batch assembles the global batch via
        # make_array_from_process_local_data
        x = rng.integers(0, cfg.vocab_size,
                         (4, cfg.context_length)).astype(np.int32)
        batch = plan.shard_batch({
            "inputs": x,
            "targets": np.roll(x, -1, 1).astype(np.int32),
            "weights": np.ones_like(x, np.float32),
        })
        assert batch["inputs"].shape[0] == 4 * nproc  # global batch
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all(), losses

    # gather_full: process_allgather branch (every host gets full values)
    full = gather_full(state["trainable"])
    assert full["blocks"]["attn"]["wq"].shape[0] == cfg.n_layers

    # checkpoint round-trip with the leaf-at-a-time collective ordering
    save_checkpoint(ckdir, state, extra_metadata={"global_step": 3})
    sync_global_devices("ckpt_written")
    template = plan.shard_state(
        init_train_state(init_params(cfg, jax.random.PRNGKey(9)), opt,
                         jax.random.PRNGKey(0)))
    restored = load_checkpoint(ckdir, template,
                               shardings=plan.state_shardings(template))
    np.testing.assert_array_equal(
        gather_full(restored["trainable"])["blocks"]["attn"]["wq"],
        full["blocks"]["attn"]["wq"])
    assert int(restored["step"]) == 3

    # RESUME: training continues from the restored state (the path the
    # reference lacks entirely, SURVEY.md §5)
    x = rng.integers(0, cfg.vocab_size,
                     (4, cfg.context_length)).astype(np.int32)
    batch = plan.shard_batch({
        "inputs": x,
        "targets": np.roll(x, -1, 1).astype(np.int32),
        "weights": np.ones_like(x, np.float32),
    })
    restored, m = step(restored, batch)
    assert np.isfinite(float(m["loss"]))
    assert int(restored["step"]) == 4
    sync_global_devices("done")
    print(f"WORKER_{pid}_OK", flush=True)


def _run_pp(pid, nproc, cfg):
    """Multi-host pipeline parallelism (round-5 VERDICT #5): stage axis
    mapped over hosts (stage-contiguous device order), per-process
    microbatch feeds via make_array_from_process_local_data, 3 finite
    train steps."""
    import jax

    from building_llm_from_scratch_tpu.parallel import sync_global_devices
    from building_llm_from_scratch_tpu.parallel.pipeline import (
        PipelinePlan,
        make_pp_mesh,
        make_pp_train_step,
    )
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
    )

    cfg = cfg.replace(n_layers=4, context_length=16)
    # n_stages = n_processes: with the stage-contiguous device order each
    # host owns exactly one stage — the per-tick ppermute hop is the only
    # inter-host traffic
    plan = PipelinePlan(make_pp_mesh(nproc), n_micro=2)
    opt = build_optimizer(total_steps=10)
    state = plan.shard_state(init_train_state(
        init_params(cfg, jax.random.PRNGKey(0)), opt, jax.random.PRNGKey(0)))
    wq = state["trainable"]["blocks"]["attn"]["wq"]
    assert not wq.is_fully_addressable       # stage axis spans hosts
    step = make_pp_train_step(cfg, opt, plan.mesh, n_micro=plan.n_micro)

    # stage-over-hosts: every process feeds the SAME rows (the data axis
    # is host-local per stage) — fixed seed, NOT pid-dependent
    np.random.seed(0)
    losses = []
    bs = 2 * plan.mesh.shape["data"]     # Bm = bs/n_micro divides data axis
    for i in range(3):
        x = np.random.randint(0, cfg.vocab_size,
                              (bs, cfg.context_length)).astype(np.int32)
        batch = plan.shard_batch({
            "inputs": x,
            "targets": np.roll(x, -1, 1).astype(np.int32),
            "weights": np.ones_like(x, np.float32),
        })
        assert batch["inputs"].ndim == 3      # (M, Bm_global, T) feed
        assert batch["inputs"].shape[0] == plan.n_micro
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all(), losses
    sync_global_devices("pp_done")
    print(f"WORKER_{pid}_OK", flush=True)


if __name__ == "__main__":
    main()
