"""Chunked softmax cross-entropy (ops/softmax_xent.py) vs the dense path.

The fused op must be EXACT (same fp32 math) against
train_step.cross_entropy_loss over materialized logits — values and
gradients — including non-divisible vocab sizes (padding+mask path) and
0/1 loss-weight masks (the instruction-finetune collator semantics,
reference dataloader_instruction_finetune.py:33-45).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.ops.softmax_xent import (
    fused_cross_entropy_loss,
    softmax_xent,
)
from building_llm_from_scratch_tpu.training.train_step import (
    cross_entropy_loss,
)


def _case(B=2, T=64, D=32, V=101, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    x = jax.random.normal(ks[0], (B, T, D), jnp.float32)
    w = jax.random.normal(ks[1], (D, V), jnp.float32) * 0.1
    t = jax.random.randint(ks[2], (B, T), 0, V)
    return x, w, t


def _dense_loss(x, w, t, weights=None):
    logits = jnp.einsum("btd,dv->btv", x, w,
                        preferred_element_type=jnp.float32)
    return cross_entropy_loss(logits, t, weights)


@pytest.mark.parametrize("chunk", [32, 50, 101, 128])
def test_loss_matches_dense(chunk):
    x, w, t = _case()
    want = float(_dense_loss(x, w, t))
    got = float(fused_cross_entropy_loss(x, w, t, chunk=chunk))
    assert abs(got - want) < 1e-5


def test_loss_matches_dense_with_weights():
    x, w, t = _case()
    weights = (jnp.arange(64)[None, :] >= 20).astype(jnp.float32).repeat(2, 0)
    want = float(_dense_loss(x, w, t, weights))
    got = float(fused_cross_entropy_loss(x, w, t, weights, chunk=32))
    assert abs(got - want) < 1e-5


def test_gradients_match_dense():
    x, w, t = _case()
    weights = (jnp.arange(64)[None, :] >= 10).astype(jnp.float32).repeat(2, 0)

    gw_dense = jax.grad(lambda x, w: _dense_loss(x, w, t, weights),
                        argnums=(0, 1))(x, w)
    gw_fused = jax.grad(
        lambda x, w: fused_cross_entropy_loss(x, w, t, weights, chunk=32),
        argnums=(0, 1))(x, w)
    for a, b in zip(gw_fused, gw_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_gradients_match_dense_bf16():
    """bf16 hidden/head (the training dtype): grads agree within bf16
    matmul tolerance."""
    x, w, t = _case()
    xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)

    gw_dense = jax.grad(
        lambda x, w: _dense_loss(x, w, t), argnums=(0, 1))(xb, wb)
    gw_fused = jax.grad(
        lambda x, w: fused_cross_entropy_loss(x, w, t, chunk=32),
        argnums=(0, 1))(xb, wb)
    for a, b in zip(gw_fused, gw_dense):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=2e-2, rtol=2e-2)


def test_per_token_nll_matches_log_softmax():
    x, w, t = _case(B=1, T=16, D=8, V=37)
    logits = jnp.einsum("btd,dv->btv", x, w)
    want = -np.asarray(jax.nn.log_softmax(logits, axis=-1))[
        0, np.arange(16), np.asarray(t)[0]]
    got = np.asarray(softmax_xent(x[0], w, t[0], 16))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_train_step_uses_fused_path_same_loss():
    """End-to-end: the train step's first-step loss equals the dense
    computation on the same params/batch."""
    from building_llm_from_scratch_tpu.configs import ModelConfig
    from building_llm_from_scratch_tpu.models import forward, init_params
    from building_llm_from_scratch_tpu.training import (
        build_optimizer,
        init_train_state,
        make_train_step,
    )

    cfg = ModelConfig(
        name="t", vocab_size=97, context_length=32, emb_dim=16, n_heads=2,
        n_layers=2, hidden_dim=32, n_kv_groups=2, norm="rmsnorm",
        positional="rope", activation="swiglu", drop_rate=0.0, dtype="fp32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {
        "inputs": jnp.asarray(rng.integers(0, 97, (2, 32)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, 97, (2, 32)), jnp.int32),
        "weights": jnp.ones((2, 32), jnp.float32),
    }
    opt = build_optimizer(total_steps=3)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    step = make_train_step(cfg, opt, jit=False)
    _, metrics = step(state, batch)
    logits = forward(params, cfg, batch["inputs"])
    want = float(cross_entropy_loss(logits, batch["targets"],
                                    batch["weights"]))
    assert abs(float(metrics["loss"]) - want) < 1e-5
