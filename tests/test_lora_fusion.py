"""Fused multi-LoRA training (training/lora_fusion.py): parity with the
solo trainer, zero-recompile job churn, co-residency fault isolation, and
the per-job export → hot-deploy hop."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.models.lora import (
    init_lora_params,
    load_adapter,
)
from building_llm_from_scratch_tpu.models.transformer import forward
from building_llm_from_scratch_tpu.obs.metrics import configure_metrics
from building_llm_from_scratch_tpu.training import (
    build_optimizer,
    init_train_state,
    make_train_step,
    warmup_cosine_schedule,
)
from building_llm_from_scratch_tpu.training.lora_fusion import (
    FinetuneJob,
    FusedLoRATrainer,
    fleet_lr_schedule,
    init_fleet_state,
    make_fused_train_step,
    stack_fleet_batch,
)

RANK, ALPHA = 4, 8.0


def _copy(tree):
    return jax.tree_util.tree_map(lambda x: x.copy(), tree)


@pytest.fixture(scope="module")
def cfg():
    # drop_rate=0: the parity claims below are about the math, not about
    # reproducing dropout masks across different batch shapes
    return get_config("GPT2", "124M", dtype="fp32",
                      debug=True).replace(drop_rate=0.0)


@pytest.fixture(scope="module")
def base_params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


def _job_arrays(cfg, rows, seed, mask_frac=3):
    rng = np.random.default_rng(seed)
    T = cfg.context_length
    w = np.ones((rows, T), np.float32)
    w[:, : T // mask_frac] = 0.0
    return {
        "inputs": rng.integers(0, cfg.vocab_size,
                               (rows, T)).astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size,
                                (rows, T)).astype(np.int32),
        "weights": w,
    }


def _fused_batch(jobs, rows, k, horizon):
    return stack_fleet_batch(
        [{kk: jb[kk] for kk in ("inputs", "targets", "weights")}
         for jb in jobs],
        capacity=k, scaling=ALPHA / RANK, horizon=horizon)


def _set_row(pool, j, tree):
    return jax.tree_util.tree_map(lambda p, l: p.at[j].set(l), pool, tree)


def _row(tree, j):
    return jax.tree_util.tree_map(lambda a: np.asarray(a[j]), tree)


# ---------------------------------------------------------------------------
# Parity
# ---------------------------------------------------------------------------

def test_k1_fused_matches_unmerged_reference(cfg, base_params):
    """One job through the fused step IS the unmerged single-adapter
    forward with a gather: the per-job loss is bit-identical to the
    reference, and the gradients agree to float32 epsilon (the reference
    contracts dA over B·T in one matmul; the gather's transpose
    scatter-adds per-row — a different reduction tree, last-ulp only)."""
    lora = init_lora_params(cfg, base_params, jax.random.PRNGKey(1),
                            rank=RANK)
    lora = jax.tree_util.tree_map(lambda a: a + 0.01, lora)  # B nonzero
    rows = 3
    jb = _job_arrays(cfg, rows, seed=0, mask_frac=2)

    def ref_loss(l):
        logits = forward(base_params, cfg, jb["inputs"], lora=l,
                         lora_scaling=ALPHA / RANK)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(
            logp, jnp.asarray(jb["targets"])[..., None], axis=-1)[..., 0]
        w = jnp.asarray(jb["weights"])
        return (-jnp.sum(jnp.where(w > 0, ll * w, 0.0))
                / jnp.maximum(w.sum(), 1.0))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(lora)

    state = init_fleet_state(cfg, base_params, capacity=1, rank=RANK,
                             rng=jax.random.PRNGKey(123))
    state["trainable"] = _set_row(state["trainable"], 0, lora)
    step = make_fused_train_step(cfg, capacity=1, jit=False)
    batch = _fused_batch([jb], rows, 1, horizon=10)

    def fused_loss(pool):
        adapter = {"pool": pool,
                   "scaling": jnp.asarray(batch["scaling"]),
                   "ids": jnp.asarray(batch["job_ids"])}
        logits = forward(base_params, cfg, jb["inputs"], adapter=adapter)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(
            logp, jnp.asarray(jb["targets"])[..., None], axis=-1)[..., 0]
        w = jnp.asarray(jb["weights"])
        return (-jnp.sum(jnp.where(w > 0, ll * w, 0.0))
                / jnp.maximum(w.sum(), 1.0))

    f_l, f_g = jax.value_and_grad(fused_loss)(state["trainable"])
    # loss: BIT-for-bit
    assert float(f_l) == float(ref_l)
    # the step's own per-job loss metric reports the same value
    _, metrics = step(state, batch)
    assert float(metrics["loss"][0]) == float(ref_l)
    # grads: same math, epsilon-level reduction-order drift only (pinned)
    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(ref_g))
    fused_leaves = [np.asarray(l[0]) for l in
                    jax.tree_util.tree_leaves(jax.device_get(f_g))]
    for a, b in zip(ref_leaves, fused_leaves):
        assert a.shape == b.shape
        np.testing.assert_allclose(a, b, atol=3e-7, rtol=0)


def test_k3_fused_tracks_each_solo_run(cfg, base_params):
    """Three jobs co-trained fused land within float-epsilon of their own
    solo ``--use_lora`` runs (the merged-weights optax trainer): per-job
    losses equal at 1e-5 rtol and adapter params within 5e-6 after 6
    steps — fusion changes the schedule of the computation, not the
    training each tenant gets."""
    k, rows, n, horizon = 3, 2, 6, 8
    jobs = []
    for j in range(k):
        jb = _job_arrays(cfg, rows, seed=j)
        jb["lora"] = init_lora_params(cfg, base_params,
                                      jax.random.PRNGKey(10 + j),
                                      rank=RANK)
        jobs.append(jb)

    solo_final = []
    for j in range(k):
        sched = warmup_cosine_schedule(5e-4, 1e-5, 1e-6, 2, horizon)
        opt = build_optimizer(total_steps=horizon, warmup_steps=2,
                              schedule=sched)
        state = init_train_state(_copy(jobs[j]["lora"]), opt,
                                 jax.random.PRNGKey(123),
                                 frozen=_copy(base_params))
        step = make_train_step(cfg, opt, lora_rank=RANK, lora_alpha=ALPHA,
                               lr_schedule=sched)
        for _ in range(n):
            state, m = step(state, {kk: jobs[j][kk] for kk in
                                    ("inputs", "targets", "weights")})
        solo_final.append((float(jax.device_get(m["loss"])),
                           jax.device_get(state["trainable"])))

    fstate = init_fleet_state(cfg, base_params, capacity=k, rank=RANK,
                              rng=jax.random.PRNGKey(123))
    for j in range(k):
        fstate["trainable"] = _set_row(fstate["trainable"], j,
                                       _copy(jobs[j]["lora"]))
    fstep = make_fused_train_step(cfg, capacity=k, warmup_steps=2)
    batch = _fused_batch(jobs, rows, k, horizon)
    for _ in range(n):
        fstate, fm = fstep(fstate, batch)
    floss = jax.device_get(fm["loss"])
    ftrain = jax.device_get(fstate["trainable"])
    for j in range(k):
        solo_loss, solo_params = solo_final[j]
        assert floss[j] == pytest.approx(solo_loss, rel=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(solo_params),
                        jax.tree_util.tree_leaves(_row(ftrain, j))):
            np.testing.assert_allclose(np.asarray(a), b, atol=5e-6,
                                       rtol=0)


def test_per_job_lr_schedule_matches_solo_schedule(cfg):
    """The traced-horizon vectorized schedule reproduces
    ``warmup_cosine_schedule`` elementwise — two jobs with different
    horizons each decay over their OWN length inside one program."""
    horizons = np.asarray([7, 23], np.int32)
    for count in range(10):
        got = fleet_lr_schedule(
            jnp.full((2,), count, jnp.int32), jnp.asarray(horizons),
            peak_lr=5e-4, initial_lr=1e-5, min_lr=1e-6, warmup_steps=3)
        for i, horizon in enumerate(horizons):
            ref = warmup_cosine_schedule(5e-4, 1e-5, 1e-6, 3,
                                         int(horizon))(count)
            assert float(got[i]) == pytest.approx(float(ref), rel=1e-6)


# ---------------------------------------------------------------------------
# Engine: churn, isolation, export
# ---------------------------------------------------------------------------

def _make_job(cfg, name, *, rows=2, steps_per_epoch=2, n_epochs=1,
              seed=0, export_path=None, init=None):
    batches = [_job_arrays(cfg, rows, seed=seed + i)
               for i in range(steps_per_epoch)]

    def make_batches(epoch):
        for b in batches:
            yield b["inputs"], b["targets"], b["weights"]

    return FinetuneJob(name=name, make_batches=make_batches,
                       steps_per_epoch=steps_per_epoch, n_epochs=n_epochs,
                       export_path=export_path, init=init)


def test_join_finish_zero_recompile_and_deploy(cfg, base_params, tmp_path):
    """Job churn is data: a short job finishing, a queued job hot-joining
    its freed slot, and per-job exports all happen under the frozen
    CompileWatcher with ZERO recompiles; each artifact loads into a live
    AdapterRegistry (the train→deploy hop)."""
    from building_llm_from_scratch_tpu.serving.adapters import (
        AdapterRegistry,
    )

    registry = AdapterRegistry(cfg, base_params, capacity=4,
                               max_rank=RANK)
    fleet = FusedLoRATrainer(cfg, base_params, capacity=2, rank=RANK,
                             alpha=ALPHA, rows_per_job=2, log_every=1,
                             export_dir=str(tmp_path), deploy=registry)
    # capacity 2, three jobs of different lengths: "late" must hot-join
    # the slot "fast" frees, mid-run
    fleet.add_job(_make_job(cfg, "fast", steps_per_epoch=2, seed=0))
    fleet.add_job(_make_job(cfg, "slow", steps_per_epoch=3, n_epochs=2,
                            seed=10))
    fleet.add_job(_make_job(cfg, "late", steps_per_epoch=2, seed=20))
    fleet.run()
    assert [j.status for j in fleet.jobs] == ["done"] * 3
    assert fleet.n_recompiles == 0
    for job in fleet.jobs:
        assert os.path.isfile(job.artifact)
        lora, meta = load_adapter(job.artifact)
        assert meta["rank"] == RANK
        # deployed: the registry serves the tenant by name
        assert registry.lookup(job.name) is not None
    assert registry.n_loaded == 3


@pytest.mark.slow
def test_nonfinite_job_retires_alone_coresidents_bit_identical(
        cfg, base_params, tmp_path):
    """Poisoning job B's adapter row mid-run retires B (no artifact, a
    ``finetune_job_failed`` event) while job A's exported adapter is
    BIT-identical to a run where B stayed healthy — co-residency costs a
    tenant nothing, even under a neighbor's divergence (the serving
    fault-isolation contract, training-side)."""
    init_a = init_lora_params(cfg, base_params, jax.random.PRNGKey(50),
                              rank=RANK)
    init_b = init_lora_params(cfg, base_params, jax.random.PRNGKey(51),
                              rank=RANK)

    def run(poison: bool, out_dir):
        mj = os.path.join(str(out_dir), "m.jsonl")
        configure_metrics(mj)
        try:
            fleet = FusedLoRATrainer(cfg, base_params, capacity=2,
                                     rank=RANK, alpha=ALPHA,
                                     rows_per_job=2, log_every=2,
                                     export_dir=str(out_dir))
            fleet.add_job(_make_job(cfg, "a", steps_per_epoch=6, seed=0,
                                    init=_copy(init_a)))
            fleet.add_job(_make_job(cfg, "b", steps_per_epoch=6, seed=9,
                                    init=_copy(init_b)))

            def hook(engine):
                if poison and engine.global_step == 3:
                    bad = engine._slots[1]
                    assert bad is not None and bad.name == "b"
                    engine.state["trainable"] = jax.tree_util.tree_map(
                        lambda p: p.at[1].set(jnp.nan),
                        engine.state["trainable"])

            fleet.on_step = hook
            fleet.run()
        finally:
            configure_metrics(None)
        rows = [json.loads(line) for line in open(mj)]
        return fleet, rows

    clean, _ = run(False, tmp_path / "clean")
    poisoned, rows = run(True, tmp_path / "poisoned")

    a_clean = next(j for j in clean.jobs if j.name == "a")
    a_pois = next(j for j in poisoned.jobs if j.name == "a")
    b_pois = next(j for j in poisoned.jobs if j.name == "b")
    assert a_pois.status == "done" and a_clean.status == "done"
    assert b_pois.status == "failed" and b_pois.artifact is None
    assert "non-finite" in b_pois.error
    failed = [r for r in rows if r.get("event") == "finetune_job_failed"]
    assert len(failed) == 1 and failed[0]["job_id"] == "b"
    assert failed[0]["reason"] == "non_finite"
    # the poisoned run never recompiled (retire is data, not shape)
    assert poisoned.n_recompiles == 0
    # job A's artifact: bit-identical across the two runs
    lora_clean, _ = load_adapter(a_clean.artifact)
    lora_pois, _ = load_adapter(a_pois.artifact)
    for x, y in zip(jax.tree_util.tree_leaves(lora_clean),
                    jax.tree_util.tree_leaves(lora_pois)):
        assert np.array_equal(x, y)


def test_zero_supervision_job_retires_instead_of_exporting(
        cfg, base_params, tmp_path):
    """A job whose every row is fully loss-masked (the
    template-overflows-context hazard) never trained: it must retire as
    failed (``no_supervised_tokens``) instead of exporting and deploying
    a zero-delta adapter as 'done'."""
    masked = _job_arrays(cfg, 2, seed=0)
    masked["weights"][:] = 0.0

    def make_batches(epoch):
        yield masked["inputs"], masked["targets"], masked["weights"]

    mj = os.path.join(str(tmp_path), "m.jsonl")
    configure_metrics(mj)
    try:
        fleet = FusedLoRATrainer(cfg, base_params, capacity=2, rank=RANK,
                                 alpha=ALPHA, rows_per_job=2, log_every=1,
                                 export_dir=str(tmp_path))
        fleet.add_job(FinetuneJob(name="masked",
                                  make_batches=make_batches,
                                  steps_per_epoch=1, n_epochs=2))
        fleet.add_job(_make_job(cfg, "healthy", steps_per_epoch=2,
                                seed=1))
        fleet.run()
    finally:
        configure_metrics(None)
    bad = next(j for j in fleet.jobs if j.name == "masked")
    good = next(j for j in fleet.jobs if j.name == "healthy")
    assert bad.status == "failed" and bad.artifact is None
    assert "no_supervised_tokens" in bad.error
    assert good.status == "done" and os.path.isfile(good.artifact)
    rows = [json.loads(line) for line in open(mj)]
    failed = [r for r in rows if r.get("event") == "finetune_job_failed"]
    assert failed and failed[0]["reason"] == "no_supervised_tokens"


def test_fast_job_exports_before_slow_job_finishes(cfg, base_params,
                                                   tmp_path):
    """Per-JOB export discipline: the fast tenant's ``adapter_save``
    lands while the slow job is still training (event order pinned) —
    deployments never wait for the whole fleet."""
    mj = os.path.join(str(tmp_path), "m.jsonl")
    configure_metrics(mj)
    try:
        fleet = FusedLoRATrainer(cfg, base_params, capacity=2, rank=RANK,
                                 alpha=ALPHA, rows_per_job=2, log_every=1,
                                 export_dir=str(tmp_path))
        fleet.add_job(_make_job(cfg, "fast", steps_per_epoch=2, seed=0))
        fleet.add_job(_make_job(cfg, "slow", steps_per_epoch=4,
                                n_epochs=2, seed=10))
        fleet.run()
    finally:
        configure_metrics(None)
    rows = [json.loads(line) for line in open(mj)]
    kinds = [(r.get("event"), r.get("job_id")) for r in rows
             if r.get("type") == "event"]
    fast_save = kinds.index(("adapter_save", "fast"))
    slow_done = kinds.index(("finetune_job_done", "slow"))
    assert fast_save < slow_done
    # both artifacts exist and are distinct files
    paths = {j.artifact for j in fleet.jobs}
    assert len(paths) == 2 and all(os.path.isfile(p) for p in paths)


def test_forward_adapter_mixed_ids_matches_per_row_lora(cfg, base_params):
    """The jobs-axis threading unit: a mixed-ids batch through
    ``forward(adapter=)`` equals running each row with its own adapter
    through the existing ``forward(lora=)`` path (id −1 rows equal the
    bare base forward bit-for-bit)."""
    lora0 = init_lora_params(cfg, base_params, jax.random.PRNGKey(2),
                             rank=RANK)
    lora0 = jax.tree_util.tree_map(lambda a: a + 0.02, lora0)
    lora1 = init_lora_params(cfg, base_params, jax.random.PRNGKey(3),
                             rank=RANK)
    lora1 = jax.tree_util.tree_map(lambda a: a - 0.015, lora1)
    pool = jax.tree_util.tree_map(
        lambda a, b: jnp.stack([a, b]), lora0, lora1)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size,
                          (3, cfg.context_length)).astype(np.int32)
    ids = np.asarray([1, -1, 0], np.int32)
    scaling = np.full((2,), ALPHA / RANK, np.float32)
    got = forward(base_params, cfg, tokens,
                  adapter={"pool": pool, "scaling": scaling, "ids": ids})
    ref1 = forward(base_params, cfg, tokens[1:2])
    ref0 = forward(base_params, cfg, tokens[2:3], lora=lora0,
                   lora_scaling=ALPHA / RANK)
    ref_1 = forward(base_params, cfg, tokens[0:1], lora=lora1,
                    lora_scaling=ALPHA / RANK)
    # the id -1 row is the bare base path EXACTLY (clamped gather x zero
    # scale = exact zero delta)
    assert np.array_equal(np.asarray(got[1]), np.asarray(ref1[0]))
    np.testing.assert_allclose(np.asarray(got[2]), np.asarray(ref0[0]),
                               atol=1e-5, rtol=0)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref_1[0]),
                               atol=1e-5, rtol=0)


# ---------------------------------------------------------------------------
# CLI flag surface
# ---------------------------------------------------------------------------

def test_fleet_flag_validation(tmp_path):
    from building_llm_from_scratch_tpu.args import get_args

    records = [{"instruction": "a", "input": "", "output": "b"}] * 4
    jpath = os.path.join(str(tmp_path), "j.json")
    with open(jpath, "w") as f:
        json.dump(records, f)
    data = os.path.join(str(tmp_path), "data")
    os.makedirs(data)

    base = ["--debug", "--byte_tokenizer", "--output_dir",
            os.path.join(str(tmp_path), "out")]
    # happy path parses
    args = get_args(["--mode", "finetune_fleet",
                     "--fleet_jobs", f"a={jpath}"] + base)
    assert args.mode == "finetune_fleet"
    # fleet mode without jobs
    with pytest.raises(ValueError, match="fleet_jobs"):
        get_args(["--mode", "finetune_fleet"] + base)
    # missing records file
    with pytest.raises(FileNotFoundError):
        get_args(["--mode", "finetune_fleet",
                  "--fleet_jobs", "a=/nonexistent.json"] + base)
    # fleet flags stray outside the mode
    with pytest.raises(ValueError, match="finetune_fleet"):
        get_args(["--data_dir", data,
                  "--fleet_jobs", f"a={jpath}"] + base)
    # --use_lora / --finetune / --save_adapter are solo-run flags
    for extra in (["--use_lora"], ["--finetune"],
                  ["--save_adapter", "x.npz"]):
        with pytest.raises(ValueError):
            get_args(["--mode", "finetune_fleet",
                      "--fleet_jobs", f"a={jpath}"] + base + extra)


def test_job_from_records_plain_style(cfg):
    from building_llm_from_scratch_tpu.data.tokenizers import (
        build_tokenizer,
    )

    tok = build_tokenizer("GPT2", None, fallback_byte=True)
    records = [{"instruction": "ab", "input": "", "output": "cdef"}
               for _ in range(5)]
    job = FinetuneJob.from_records(
        "t", records, tok, max_length=cfg.context_length,
        rows_per_step=2, n_epochs=2, pad_token_id=cfg.eos_id, seed=1,
        style="plain")
    assert job.total_steps == 4          # 5 records // 2 rows, x2 epochs
    inp, tgt, w = job.next_rows()
    assert inp.shape == (2, cfg.context_length)
    # plain style leaves supervised positions inside the tiny context
    assert w.sum() > 0
    # too-few records refuse loudly
    with pytest.raises(ValueError, match="cannot fill"):
        FinetuneJob.from_records(
            "t2", records[:1], tok, max_length=cfg.context_length,
            rows_per_step=2, n_epochs=1, pad_token_id=cfg.eos_id)


# ---------------------------------------------------------------------------
# Slot-aligned adapter application (ROADMAP PR 12 follow-up)
# ---------------------------------------------------------------------------

def test_aligned_matches_gather_path_k3(cfg, base_params):
    """The slot-aligned ``(J, R*T)`` application (default) trains each
    job identically to the historical per-row gather: k=3 per-job
    losses within 1e-5 and adapter params within 5e-6 after 6 steps —
    the reshape removes the rows_per_job-fold A/B duplication, not any
    math. (The HLO difference is what the re-baselined
    ``micro_lora_fusion`` fingerprint pins.)"""
    k, rows, n, horizon = 3, 2, 6, 8
    jobs = []
    for j in range(k):
        jb = _job_arrays(cfg, rows, seed=j)
        jb["lora"] = init_lora_params(cfg, base_params,
                                      jax.random.PRNGKey(10 + j),
                                      rank=RANK)
        jobs.append(jb)
    batch = _fused_batch(jobs, rows, k, horizon)

    def run(aligned):
        state = init_fleet_state(cfg, base_params, capacity=k, rank=RANK,
                                 rng=jax.random.PRNGKey(123))
        for j in range(k):
            state["trainable"] = _set_row(state["trainable"], j,
                                          _copy(jobs[j]["lora"]))
        step = make_fused_train_step(cfg, capacity=k, warmup_steps=2,
                                     aligned=aligned)
        losses = []
        for _ in range(n):
            state, m = step(state, batch)
            losses.append(np.asarray(jax.device_get(m["loss"])))
        return np.stack(losses), jax.device_get(state["trainable"])

    l_aligned, p_aligned = run(True)
    l_gather, p_gather = run(False)
    np.testing.assert_allclose(l_aligned, l_gather, rtol=1e-5, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_aligned),
                    jax.tree_util.tree_leaves(p_gather)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6, rtol=0)


def test_aligned_rejects_misaligned_batch(cfg, base_params):
    """The aligned path is only valid for the stack_fleet_batch layout:
    a row count not divisible by rows_per_job is a loud error, not a
    silently mis-bucketed delta."""
    from building_llm_from_scratch_tpu.models.transformer import (
        forward_hidden,
    )

    pool = jax.tree_util.tree_map(
        lambda a: jnp.zeros((2,) + a.shape, a.dtype),
        init_lora_params(cfg, base_params, jax.random.PRNGKey(0),
                         rank=RANK))
    tokens = np.zeros((3, cfg.context_length), np.int32)  # 3 % 2 != 0
    with pytest.raises(ValueError, match="rows_per_job"):
        forward_hidden(base_params, cfg, tokens,
                       adapter={"pool": pool,
                                "scaling": jnp.ones((2,), jnp.float32),
                                "rows_per_job": 2})


# ---------------------------------------------------------------------------
# Fleet checkpoint / resume (PR 1 machinery on the stacked pool state)
# ---------------------------------------------------------------------------

def _ckpt_jobs(cfg, tok, n_epochs=3):
    def records(vocab):
        return [{"instruction": vocab[i % 4] * 2, "input": "",
                 "output": vocab[(i + 1) % 4] * 3} for i in range(8)]

    return [FinetuneJob.from_records(
        name, records(vocab), tok, max_length=cfg.context_length,
        rows_per_step=2, n_epochs=n_epochs, pad_token_id=cfg.eos_id,
        style="plain") for name, vocab in (("ja", "abcd"), ("jb", "wxyz"))]


def _tracked_run(engine, record, stop_at=None, signal_at=None):
    """Run a fleet recording each flushed step's per-job losses; with
    ``signal_at``, deliver a REAL SIGTERM (to this process, through a
    GracefulStopper) once global_step reaches it."""
    import os
    import signal as _signal

    from building_llm_from_scratch_tpu.training.resilience import (
        GracefulStopper,
    )

    orig_flush = engine._flush

    def wrapped(*a, **kw):
        orig_flush(*a, **kw)
        if engine._last_fetched is not None:
            record[engine.global_step] = [
                round(float(x), 10) for x in engine._last_fetched["loss"]]

    engine._flush = wrapped
    if signal_at is not None:
        def on_step(eng):
            if eng.global_step == signal_at:
                os.kill(os.getpid(), _signal.SIGTERM)

        engine.on_step = on_step
        with GracefulStopper() as stopper:
            engine.run(stopper=stopper)
    else:
        engine.run()
    return engine


@pytest.mark.slow
def test_fleet_sigterm_resume_bit_for_bit(cfg, base_params, tmp_path):
    """SIGTERM mid-fleet -> step-boundary checkpoint -> `--resume auto`
    discovery -> per-job loss trajectories continue BIT-FOR-BIT: the
    stacked pool state round-trips through the PR 1 sharded-manifest
    checkpoint, and each job's batch cursor fast-forwards to the exact
    (epoch, index) the preempted run stopped at."""
    from building_llm_from_scratch_tpu.data.tokenizers import (
        build_tokenizer,
    )
    from building_llm_from_scratch_tpu.training.resilience import (
        find_latest_valid_checkpoint,
    )

    tok = build_tokenizer("GPT2", None, fallback_byte=True)

    def make(ckpt_dir=None):
        eng = FusedLoRATrainer(
            cfg, base_params, tokenizer=tok, capacity=2, rank=RANK,
            alpha=ALPHA, rows_per_job=2, log_every=1,
            export_dir=str(tmp_path / "adapters"),
            ckpt_dir=ckpt_dir, compile_telemetry=False)
        for job in _ckpt_jobs(cfg, tok):
            eng.add_job(job)
        return eng

    reference = {}
    _tracked_run(make(), reference)
    assert len(reference) == 12          # 2 jobs x (8//2) x 3 epochs

    ckpt_dir = str(tmp_path / "ckpts")
    resumed = {}
    first = _tracked_run(make(ckpt_dir), resumed, signal_at=5)
    assert first.preempted
    assert all(j.status == "running" for j in first.jobs)
    found = find_latest_valid_checkpoint(ckpt_dir)
    assert found is not None and found.endswith("model_pg_5")

    second = make(ckpt_dir)
    second.restore(found)
    assert second.global_step == 5
    _tracked_run(second, resumed)
    assert not second.preempted
    assert all(j.status == "done" for j in second.jobs)
    assert resumed == reference          # bit-for-bit, pre AND post resume


def test_fleet_restore_refuses_mismatched_shape(cfg, base_params,
                                                tmp_path):
    """A checkpoint from a different fleet geometry (capacity/rank) or a
    non-fleet checkpoint refuses loudly instead of silently restoring
    the wrong pool."""
    from building_llm_from_scratch_tpu.data.tokenizers import (
        build_tokenizer,
    )

    tok = build_tokenizer("GPT2", None, fallback_byte=True)
    eng = FusedLoRATrainer(cfg, base_params, tokenizer=tok, capacity=2,
                           rank=RANK, alpha=ALPHA, rows_per_job=2,
                           ckpt_dir=str(tmp_path),
                           compile_telemetry=False)
    for job in _ckpt_jobs(cfg, tok, n_epochs=1):
        eng.add_job(job)
    eng._admit_pending()
    path = eng.save_checkpoint()
    assert path is not None

    other = FusedLoRATrainer(cfg, base_params, tokenizer=tok, capacity=3,
                             rank=RANK, alpha=ALPHA, rows_per_job=2,
                             compile_telemetry=False)
    with pytest.raises(ValueError, match="capacity/rank"):
        other.restore(path)

    # a non-fleet manifest (no fleet flag) refuses before touching state
    from building_llm_from_scratch_tpu.training.checkpoint import (
        save_checkpoint,
    )

    plain = str(tmp_path / "model_pg_99")
    save_checkpoint(plain, {"w": jnp.zeros((2,))},
                    extra_metadata={"global_step": 99})
    with pytest.raises(ValueError, match="not a fleet checkpoint"):
        eng.restore(plain)


def test_resume_discovery_filters_by_run_mode(cfg, base_params, tmp_path):
    """Trainer and fleet checkpoints share the model_pg_ prefix and often
    one --output_dir: each mode's AUTO-discovery must skip the other's
    checkpoints quietly (start fresh / find an older matching one)
    instead of picking the wrong type and dying in the restore."""
    from building_llm_from_scratch_tpu.data.tokenizers import (
        build_tokenizer,
    )
    from building_llm_from_scratch_tpu.training.checkpoint import (
        save_checkpoint,
    )
    from building_llm_from_scratch_tpu.training.resilience import (
        resolve_resume,
    )

    out = str(tmp_path)
    fleet_pred = lambda meta: bool(meta.get("fleet"))      # noqa: E731
    train_pred = lambda meta: not meta.get("fleet")        # noqa: E731

    # a TRAINER checkpoint alone: fleet auto-resume starts fresh
    save_checkpoint(os.path.join(out, "model_pg_7"),
                    {"w": jnp.zeros((2,))},
                    extra_metadata={"global_step": 7})
    assert resolve_resume("auto", None, out, predicate=fleet_pred) is None
    # ...while trainer auto-resume finds it
    got = resolve_resume("auto", None, out, predicate=train_pred)
    assert got is not None and got.endswith("model_pg_7")

    # add a NEWER fleet checkpoint: each mode now finds its own
    tok = build_tokenizer("GPT2", None, fallback_byte=True)
    eng = FusedLoRATrainer(cfg, base_params, tokenizer=tok, capacity=2,
                           rank=RANK, alpha=ALPHA, rows_per_job=2,
                           ckpt_dir=out, compile_telemetry=False)
    for job in _ckpt_jobs(cfg, tok, n_epochs=1):
        eng.add_job(job)
    eng._admit_pending()
    eng.global_step = 9
    eng.save_checkpoint()
    got = resolve_resume("auto", None, out, predicate=fleet_pred)
    assert got is not None and got.endswith("model_pg_9")
    got = resolve_resume("auto", None, out, predicate=train_pred)
    assert got is not None and got.endswith("model_pg_7")
    # an EXPLICIT wrong-type path still refuses loudly in restore()
    with pytest.raises(ValueError, match="not a fleet checkpoint"):
        eng.restore(os.path.join(out, "model_pg_7"))


def test_resume_discovery_survives_vanished_candidate(tmp_path,
                                                      monkeypatch):
    """Discovery must never raise: a candidate that becomes unreadable
    between listing and the predicate's metadata read (a concurrent
    run's retention GC deleting it) is skipped like any other invalid
    checkpoint instead of crashing --resume auto."""
    from building_llm_from_scratch_tpu.training import (
        checkpoint as ckpt_mod,
    )
    from building_llm_from_scratch_tpu.training.resilience import (
        find_latest_valid_checkpoint,
    )

    out = str(tmp_path)
    for step in (3, 5):
        ckpt_mod.save_checkpoint(
            os.path.join(out, f"model_pg_{step}"),
            {"w": jnp.zeros((2,))}, extra_metadata={"global_step": step})

    # model_pg_5 survives LISTING (first metadata read per path) but
    # "vanishes" before the predicate's own read (the second) — exactly
    # the GC race window
    real_metadata = ckpt_mod.checkpoint_metadata
    calls = {}

    def racing_metadata(path):
        calls[path] = calls.get(path, 0) + 1
        if path.endswith("model_pg_5") and calls[path] >= 2:
            raise ValueError("manifest.json is missing (deleted by GC)")
        return real_metadata(path)

    monkeypatch.setattr(ckpt_mod, "checkpoint_metadata", racing_metadata)
    got = find_latest_valid_checkpoint(out, predicate=lambda meta: True)
    assert got is not None and got.endswith("model_pg_3")
