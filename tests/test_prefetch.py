"""Host-overlap tests: prefetcher order/parity/shutdown, tokenize-once
cache, and async checkpointing.

The load-bearing property is BIT-IDENTICAL training under overlap: the
prefetcher must yield exactly the synchronous iterator's batch sequence
(shuffle + multi-epoch + mid-epoch cursor resume), and an async save must
produce a checkpoint indistinguishable from the synchronous writer's.
"""

import itertools
import os
import threading
import time

import jax
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.data import (
    ByteTokenizer,
    Prefetcher,
    PretrainLoader,
    TokenCache,
)
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.training import (
    AsyncCheckpointer,
    Trainer,
    build_optimizer,
    init_train_state,
    load_checkpoint,
    save_checkpoint,
)
from building_llm_from_scratch_tpu.training.resilience import (
    validate_checkpoint,
)

CORPUS = "the quick brown fox jumps over the lazy dog. " * 220

# much smaller corpus for the Trainer integration runs: enough batches
# for several cadence windows + periodic saves, small enough that the
# two-run A/B parity tests stay well inside the tier-1 time budget
TRAIN_CORPUS = "the quick brown fox jumps over the lazy dog. " * 40


def tiny_cfg(**kw):
    return get_config("GPT2", "124M", debug=True, **kw)


def _worker_threads():
    return [t for t in threading.enumerate()
            if "prefetch-worker" in t.name or "async-ckpt" in t.name]


# ---------------------------------------------------------------------------
# Prefetcher: order, exceptions, shutdown
# ---------------------------------------------------------------------------

def _loader_and_ds(tmp_path, batch_size=2):
    tok = ByteTokenizer()
    cfg = tiny_cfg()
    f = tmp_path / "corpus.txt"
    f.write_text(CORPUS)
    loader = PretrainLoader(tok, batch_size=batch_size,
                            max_length=cfg.context_length)
    train, val = loader.create_datasets_for_file(str(f),
                                                eos_text="<|endoftext|>")
    return loader, train, val


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_prefetcher_bit_identical_sequence(tmp_path, depth):
    """Shuffled multi-epoch batch stream through the prefetcher ==
    the synchronous iterator, batch for batch, bit for bit."""
    loader, train, _ = _loader_and_ds(tmp_path)
    for epoch in (0, 1):
        sync = list(loader.batches(train, shuffle=True, epoch=epoch))
        pf = Prefetcher(loader.batches(train, shuffle=True, epoch=epoch),
                        depth)
        try:
            fetched = list(pf)
        finally:
            pf.close()
        assert len(fetched) == len(sync) > 0
        for (sx, sy), (fx, fy) in zip(sync, fetched):
            np.testing.assert_array_equal(sx, fx)
            np.testing.assert_array_equal(sy, fy)
    assert not _worker_threads()


def test_prefetcher_mid_epoch_resume_parity(tmp_path):
    """The cursor fast-forward contract: islice BEFORE wrapping, so the
    prefetched resumed stream equals the synchronous resumed stream."""
    loader, train, _ = _loader_and_ds(tmp_path)
    skip = 3
    sync = list(itertools.islice(loader.batches(train, epoch=0), skip, None))
    pf = Prefetcher(itertools.islice(loader.batches(train, epoch=0),
                                     skip, None), 2)
    try:
        fetched = list(pf)
    finally:
        pf.close()
    assert len(fetched) == len(sync) > 0
    for (sx, _), (fx, _) in zip(sync, fetched):
        np.testing.assert_array_equal(sx, fx)


def test_prefetcher_worker_exception_reraised_at_consumer():
    def boom():
        yield np.zeros(2)
        yield np.ones(2)
        raise RuntimeError("tokenizer exploded")

    pf = Prefetcher(boom(), 2)
    try:
        got = [next(pf), next(pf)]
        assert len(got) == 2
        with pytest.raises(RuntimeError, match="tokenizer exploded"):
            next(pf)
    finally:
        pf.close()
    assert not _worker_threads()


def test_prefetcher_close_mid_stream_never_leaks_thread():
    """close() with the worker blocked on a FULL queue (the shutdown path
    a preemption stop / watchdog halt takes) must join promptly."""
    def endless():
        i = 0
        while True:
            yield np.full(4, i)
            i += 1

    pf = Prefetcher(endless(), 2)
    assert (next(pf) == 0).all()         # worker running, queue refills
    time.sleep(0.05)                     # let the queue fill up again
    pf.close()
    pf.close()                           # idempotent
    assert not pf.alive
    assert not _worker_threads()


def test_prefetcher_place_fn_runs_once_per_batch():
    calls = []

    def place(x):
        calls.append(int(x[0]))
        return x * 10

    src = [np.full(2, i) for i in range(5)]
    pf = Prefetcher(iter(src), 2, place_fn=place, place_in_worker=False)
    try:
        out = list(pf)
    finally:
        pf.close()
    assert [int(x[0]) for x in out] == [0, 10, 20, 30, 40]
    assert calls == [0, 1, 2, 3, 4]


def test_prefetcher_counts_stalls_on_slow_producer():
    def slow():
        for i in range(4):
            time.sleep(0.05)
            yield i

    pf = Prefetcher(slow(), 2)
    try:
        assert list(pf) == [0, 1, 2, 3]
    finally:
        pf.close()
    # first pop's wait is startup (excluded); the rest starved
    assert pf.stalls >= 2
    assert pf.pops == 4


# ---------------------------------------------------------------------------
# Tokenize-once cache
# ---------------------------------------------------------------------------

def test_create_datasets_for_file_matches_text_path(tmp_path):
    """Cached per-file datasets == the historical text path, window for
    window (the trailing eos append included)."""
    tok = ByteTokenizer()
    cfg = tiny_cfg()
    f = tmp_path / "corpus.txt"
    f.write_text(CORPUS)
    loader = PretrainLoader(tok, batch_size=2, max_length=cfg.context_length)
    ref_train, ref_val = loader.create_datasets(
        CORPUS + " <|endoftext|> ")
    got_train, got_val = loader.create_datasets_for_file(
        str(f), eos_text="<|endoftext|>")
    np.testing.assert_array_equal(ref_train.inputs, got_train.inputs)
    np.testing.assert_array_equal(ref_train.targets, got_train.targets)
    np.testing.assert_array_equal(ref_val.inputs, got_val.inputs)
    # and the cache actually short-circuits: poison encode, hit again
    loader.tokenizer.encode = None       # would TypeError if called
    again, _ = loader.create_datasets_for_file(str(f),
                                               eos_text="<|endoftext|>")
    np.testing.assert_array_equal(again.inputs, got_train.inputs)


def test_token_cache_total_steps_prepass_warms_epochs(tmp_path):
    """get_total_steps_epoch must tokenize each file exactly once AND leave
    the cache warm for the training epochs that follow."""
    calls = []

    class CountingTok(ByteTokenizer):
        def encode(self, text, allowed_special=None):
            calls.append(len(text))
            return super().encode(text, allowed_special=allowed_special)

    cfg = tiny_cfg()
    files = []
    for i in range(2):
        f = tmp_path / f"c{i}.txt"
        f.write_text(CORPUS)
        files.append(str(f))
    loader = PretrainLoader(CountingTok(), batch_size=2,
                            max_length=cfg.context_length)
    total = loader.get_total_steps_epoch(files)
    assert total > 0
    # the cache-key fingerprint probe encodes one short string per
    # tokenizer instance; only corpus-sized encodes count here
    probe_len = len(TokenCache._PROBE)
    corpus_calls = [c for c in calls if c != probe_len]
    n_after_prepass = len(corpus_calls)
    assert n_after_prepass == 4          # 2 files x (train + val split)
    # two "epochs" over both files: all cache hits, zero new encodes
    for _ in range(2):
        for f in files:
            loader.create_datasets_for_file(f, eos_text="<|endoftext|>")
    assert len([c for c in calls if c != probe_len]) == n_after_prepass
    # matches the dataset-derived count exactly
    train, _ = loader.create_datasets_for_file(files[0],
                                               eos_text="<|endoftext|>")
    assert total == 2 * loader.num_batches(train)


def test_token_cache_disk_roundtrip_and_invalidation(tmp_path):
    cache_dir = tmp_path / "tokcache"
    f = tmp_path / "corpus.txt"
    f.write_text(CORPUS)
    cfg = tiny_cfg()

    def fresh_loader():
        return PretrainLoader(ByteTokenizer(), batch_size=2,
                              max_length=cfg.context_length,
                              token_cache_dir=str(cache_dir))

    l1 = fresh_loader()
    t1, _ = l1.create_datasets_for_file(str(f), eos_text="<|endoftext|>")
    assert len(os.listdir(cache_dir)) == 1
    # a new loader (relaunch) hits the DISK cache: the corpus is never
    # re-encoded (only the short per-tokenizer fingerprint probe is allowed)
    l2 = fresh_loader()
    real_encode = l2.tokenizer.encode

    def guarded(text, **kw):
        assert len(text) <= len(TokenCache._PROBE), \
            "corpus re-encoded despite a valid disk cache entry"
        return real_encode(text, **kw)

    l2.tokenizer.encode = guarded
    t2, _ = l2.create_datasets_for_file(str(f), eos_text="<|endoftext|>")
    np.testing.assert_array_equal(t1.inputs, t2.inputs)
    # editing the file invalidates (mtime/size key): re-tokenizes
    time.sleep(0.01)
    f.write_text(CORPUS + "changed tail!")
    l3 = fresh_loader()
    t3, _ = l3.create_datasets_for_file(str(f), eos_text="<|endoftext|>")
    assert t3.token_ids.size != t1.token_ids.size


def test_make_windows_views_are_zero_copy():
    """The satellite fix: windows must be views over the token array (no
    2x resident copy), and batch gathers must produce fresh copies."""
    from building_llm_from_scratch_tpu.data import make_windows

    ids = np.arange(5000, dtype=np.int32)
    x, y = make_windows(ids, 128, 128)
    assert x.base is not None and y.base is not None      # views
    assert np.shares_memory(x, y)                         # over one buffer
    np.testing.assert_array_equal(y, x + 1)
    batch = x[np.array([3, 1, 2])]
    assert batch.base is None or not np.shares_memory(batch, x)
    batch[0, 0] = -1                                      # writable copy
    assert x[3, 0] != -1


# ---------------------------------------------------------------------------
# Trainer integration: bit-identical losses under full overlap
# ---------------------------------------------------------------------------

def _run_trainer(tmp_path, tag, *, prefetch, async_ckpt, n_epochs=2,
                 eval_freq=10):
    cfg = tiny_cfg()
    tok = ByteTokenizer()
    datafile = tmp_path / "corpus.txt"
    if not datafile.exists():
        datafile.write_text(TRAIN_CORPUS)
    loader = PretrainLoader(tok, batch_size=4, max_length=cfg.context_length)
    trainer = Trainer(cfg, init_params(cfg, jax.random.PRNGKey(0)), tok,
                      loader, output_dir=str(tmp_path / f"out_{tag}"),
                      eval_freq=eval_freq, eval_iters=2,
                      print_sample_iter=10_000,
                      save_ckpt_freq=7, warmup_steps=2,
                      show_progress=False, prefetch=prefetch,
                      async_ckpt=async_ckpt)
    trainer.train_model([str(datafile)], n_epochs=n_epochs,
                        start_context="the ")
    return trainer


def test_trainer_prefetch_async_ckpt_bit_identical_losses(tmp_path):
    """The acceptance property: prefetch=2 + async checkpointing produces
    the EXACT loss/lr trajectory of the synchronous path (shuffle on,
    multi-epoch), while its periodic checkpoints stay manifest-valid."""
    ref = _run_trainer(tmp_path, "sync", prefetch=0, async_ckpt=False)
    fast = _run_trainer(tmp_path, "overlap", prefetch=2, async_ckpt=True)
    assert fast.global_step == ref.global_step > 0
    assert fast.tokens_seen == ref.tokens_seen
    np.testing.assert_array_equal(np.asarray(fast.train_losses),
                                  np.asarray(ref.train_losses))
    np.testing.assert_array_equal(np.asarray(fast.val_losses),
                                  np.asarray(ref.val_losses))
    np.testing.assert_array_equal(np.asarray(fast.track_lrs),
                                  np.asarray(ref.track_lrs))
    # every periodic checkpoint the async writer committed is valid
    out = tmp_path / "out_overlap"
    ckpts = [p for p in os.listdir(out) if p.startswith("model_pg_")
             and (out / p / "manifest.json").exists()]
    assert ckpts
    for p in ckpts:
        assert validate_checkpoint(str(out / p)) is None, p
    # no overlap machinery threads survive the run
    assert not _worker_threads()


def test_trainer_prefetch_eval_does_not_disturb_training_queue(tmp_path):
    """Eval cadence mid-epoch (its own small prefetcher) must not drain or
    disorder the training stream — same trajectory as eval-free windows
    would imply; cheap proxy: sync vs prefetch parity WITH frequent eval."""
    ref = _run_trainer(tmp_path, "sync_ev", prefetch=0, async_ckpt=False,
                       n_epochs=1, eval_freq=3)
    fast = _run_trainer(tmp_path, "pf_ev", prefetch=3, async_ckpt=False,
                        n_epochs=1, eval_freq=3)
    np.testing.assert_array_equal(np.asarray(fast.train_losses),
                                  np.asarray(ref.train_losses))
    np.testing.assert_array_equal(np.asarray(fast.val_losses),
                                  np.asarray(ref.val_losses))


# ---------------------------------------------------------------------------
# AsyncCheckpointer
# ---------------------------------------------------------------------------

def _tiny_state():
    cfg = tiny_cfg()
    opt = build_optimizer(total_steps=10)
    return cfg, init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                                 opt, jax.random.PRNGKey(1))


def test_async_checkpoint_valid_loadable_and_snapshot_decoupled(tmp_path):
    """An async save must produce a checkpoint that (a) passes the PR-1
    integrity validation, (b) loads through the ordinary load_checkpoint,
    and (c) captured the state AT SNAPSHOT TIME — later mutation (the
    donated next step) must not leak into the files."""
    cfg, state = _tiny_state()
    ck = AsyncCheckpointer()
    path = str(tmp_path / "model_pg_5")
    want = float(np.asarray(
        jax.tree_util.tree_leaves(state["trainable"])[0]).sum())
    ck.save(path, state, extra_metadata={"global_step": 5})
    # simulate the donated train step consuming the buffers right after
    # save() returned: the snapshot must already be decoupled
    for leaf in jax.tree_util.tree_leaves(state):
        if isinstance(leaf, jax.Array):
            leaf.delete()
    ck.wait()
    assert validate_checkpoint(path) is None
    _, template = _tiny_state()
    restored = load_checkpoint(path, template)
    got = float(np.asarray(
        jax.tree_util.tree_leaves(restored["trainable"])[0]).sum())
    assert got == want


def test_async_checkpoint_serializes_overlapping_saves(tmp_path, monkeypatch):
    """A second save must WAIT for the first commit — the two writes can
    never interleave their .tmp staging dirs."""
    import building_llm_from_scratch_tpu.training.async_checkpoint as ac

    events = []
    real_write = ac.write_snapshot

    def slow_write(ckpt_dir, snapshot):
        events.append(("start", ckpt_dir))
        time.sleep(0.3)
        out = real_write(ckpt_dir, snapshot)
        events.append(("commit", ckpt_dir))
        return out

    monkeypatch.setattr(ac, "write_snapshot", slow_write)
    _, state = _tiny_state()
    ck = AsyncCheckpointer()
    p1, p2 = str(tmp_path / "model_pg_1"), str(tmp_path / "model_pg_2")
    ck.save(p1, state, extra_metadata={"global_step": 1})
    assert ck.in_flight
    ck.save(p2, state, extra_metadata={"global_step": 2})  # must block on p1
    ck.wait()
    assert events == [("start", p1), ("commit", p1),
                      ("start", p2), ("commit", p2)]
    for p in (p1, p2):
        assert validate_checkpoint(p) is None
        assert not os.path.isdir(p + ".tmp")


def test_async_checkpoint_write_failure_reraises_on_main_thread(
        tmp_path, monkeypatch):
    import building_llm_from_scratch_tpu.training.async_checkpoint as ac

    def bad_write(ckpt_dir, snapshot):
        raise OSError("disk full")

    monkeypatch.setattr(ac, "write_snapshot", bad_write)
    _, state = _tiny_state()
    ck = AsyncCheckpointer()
    ck.save(str(tmp_path / "model_pg_1"), state,
            extra_metadata={"global_step": 1})
    with pytest.raises(RuntimeError, match="Async checkpoint write failed"):
        ck.wait()
    # error is consumed: the checkpointer stays usable
    ck.wait()


def test_async_checkpoint_overlaps_training_steps(tmp_path, monkeypatch):
    """The headline overlap property: while the (artificially slowed)
    write is in flight, real train steps keep completing."""
    import building_llm_from_scratch_tpu.training.async_checkpoint as ac
    from building_llm_from_scratch_tpu.training import make_train_step

    real_write = ac.write_snapshot

    def slow_write(ckpt_dir, snapshot):
        time.sleep(0.5)
        return real_write(ckpt_dir, snapshot)

    monkeypatch.setattr(ac, "write_snapshot", slow_write)
    cfg, state = _tiny_state()
    opt = build_optimizer(total_steps=10)
    step = make_train_step(cfg, opt)
    rng = np.random.default_rng(0)
    batch = {
        "inputs": rng.integers(0, cfg.vocab_size,
                               (2, cfg.context_length)).astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size,
                                (2, cfg.context_length)).astype(np.int32),
        "weights": np.ones((2, cfg.context_length), np.float32),
    }
    state, _ = step(state, batch)        # compile outside the overlap window
    ck = AsyncCheckpointer()
    path = str(tmp_path / "model_pg_overlap")
    ck.save(path, state, extra_metadata={"global_step": 1})
    steps_during = 0
    while ck.in_flight:
        state, metrics = step(state, batch)
        float(np.asarray(metrics["loss"]))   # force completion
        steps_during += 1
    ck.wait()
    assert steps_during >= 1
    assert validate_checkpoint(path) is None


def test_async_and_sync_checkpoints_are_interchangeable(tmp_path):
    """write_snapshot and save_checkpoint must produce checkpoints the
    same readers accept, with identical leaf contents."""
    _, state = _tiny_state()
    sync_dir = str(tmp_path / "model_pg_sync")
    async_dir = str(tmp_path / "model_pg_async")
    save_checkpoint(sync_dir, state, extra_metadata={"global_step": 3})
    ck = AsyncCheckpointer()
    ck.save(async_dir, state, extra_metadata={"global_step": 3})
    ck.wait()
    _, template1 = _tiny_state()
    _, template2 = _tiny_state()
    a = load_checkpoint(sync_dir, template1)
    b = load_checkpoint(async_dir, template2)
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
