"""Serving subsystem tests (serving/): engine correctness — slot reuse,
engine-vs-generate() token parity, mid-stream admission isolation, queue
backpressure, eos/max-token retirement, per-request RNG reproducibility,
zero-recompile discipline — plus the generate() per-row eos satellite and
the ops-level slot primitives they sit on.
"""

import http.client
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import generate
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    QueueFullError,
    RequestQueue,
    SamplingParams,
    Scheduler,
)
from building_llm_from_scratch_tpu.serving.request import Request


def tiny_cfg(ctx=64, **kw):
    base = dict(name="serve-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def solo_tokens(params, cfg, prompt, sp: SamplingParams):
    """The engine's expected output for one request: one-shot generate()
    with the matching seed/params (shared rng derivation + sampling)."""
    out, n = generate(params, cfg, np.asarray(prompt)[None],
                      max_new_tokens=sp.max_new_tokens,
                      temperature=sp.temperature, top_k=sp.top_k,
                      eos_id=(None if sp.ignore_eos
                              else (sp.eos_id if sp.eos_id is not None
                                    else cfg.eos_id)),
                      rng=jax.random.PRNGKey(sp.seed),
                      return_n_generated=True)
    Tp = len(prompt)
    return [int(t) for t in out[0, Tp: Tp + int(n[0])]]


# ---------------------------------------------------------------------------
# ops-level slot primitives
# ---------------------------------------------------------------------------

def test_slot_cache_append_per_row_offsets():
    from building_llm_from_scratch_tpu.ops.decode_step import (
        slot_cache_append,
    )

    S, H, T, D = 3, 2, 8, 4
    cache = np.zeros((S, H, T, D), np.float32)
    new = np.arange(S * H * D, dtype=np.float32).reshape(S, H, 1, D)
    lengths = np.array([0, 3, 7], np.int32)
    out = np.asarray(slot_cache_append(jnp.asarray(cache),
                                       jnp.asarray(new), lengths))
    for s, t in enumerate(lengths):
        np.testing.assert_array_equal(out[s, :, t], new[s, :, 0])
        mask = np.ones(T, bool)
        mask[t] = False
        assert (out[s][:, mask] == 0).all()
    # scalar length must equal the shared-offset DUS the decode path uses
    shared = np.asarray(slot_cache_append(jnp.asarray(cache),
                                          jnp.asarray(new),
                                          jnp.asarray(2, jnp.int32)))
    np.testing.assert_array_equal(shared[:, :, 2], new[:, :, 0])


def test_decode_attention_per_row_matches_scalar():
    from building_llm_from_scratch_tpu.ops.attention import decode_attention

    B, Hq, Hkv, D, T = 2, 4, 2, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    K = jax.random.normal(ks[1], (B, Hkv, T, D))
    V = jax.random.normal(ks[2], (B, Hkv, T, D))
    # all rows at the same length: the per-row path must equal the scalar
    scalar = decode_attention(q, K, V, q_positions=jnp.asarray([5]),
                              kv_length=jnp.asarray(6))
    perrow = decode_attention(
        q, K, V, q_positions=jnp.full((B, 1), 5),
        kv_length=jnp.full((B,), 6))
    np.testing.assert_allclose(np.asarray(scalar), np.asarray(perrow),
                               rtol=1e-6)
    # different per-row lengths: each row must match its own scalar run
    lens = jnp.asarray([3, 9])
    mixed = decode_attention(q, K, V,
                             q_positions=(lens - 1)[:, None],
                             kv_length=lens)
    for b in range(B):
        ref = decode_attention(q[b:b + 1], K[b:b + 1], V[b:b + 1],
                               q_positions=(lens[b] - 1)[None],
                               kv_length=lens[b])
        np.testing.assert_allclose(np.asarray(mixed[b]),
                                   np.asarray(ref[0]), rtol=1e-6)


# ---------------------------------------------------------------------------
# generate(): per-row eos satellite
# ---------------------------------------------------------------------------

def test_generate_per_row_eos_stops_one_row_not_the_other(model):
    cfg, params = model
    r0 = np.asarray(jax.random.randint(jax.random.PRNGKey(5), (1, 4), 2,
                                       cfg.vocab_size), np.int32)
    r1 = np.asarray(jax.random.randint(jax.random.PRNGKey(6), (1, 4), 2,
                                       cfg.vocab_size), np.int32)
    prompt = np.concatenate([r0, r1], 0)
    probe = generate(params, cfg, prompt, max_new_tokens=1)
    first = np.asarray(probe)[:, -1]
    if first[0] == first[1]:
        pytest.skip("rows greedily agree on token 0; cannot split")
    eos = int(first[0])
    out, n = generate(params, cfg, prompt, max_new_tokens=4, eos_id=eos,
                      return_n_generated=True)
    # row 0 sampled its eos first — stopped, token dropped, padded w/ eos
    assert n[0] == 0
    assert n[1] >= 1
    assert out.shape[1] == prompt.shape[1] + int(n.max())
    if n[1] > 0:
        assert (out[0, prompt.shape[1]:] == eos).all()
    # escape hatch: the reference's batch-global quirk — row 0's eos
    # neither stops it nor is dropped
    ref = generate(params, cfg, prompt, max_new_tokens=4, eos_id=eos,
                   ref_eos_semantics=True)
    assert ref.shape[1] == prompt.shape[1] + 4
    assert ref[0, prompt.shape[1]] == eos


# ---------------------------------------------------------------------------
# engine correctness
# ---------------------------------------------------------------------------

def test_engine_matches_generate_greedy_and_sampled(model):
    """Token-level engine-vs-generate() parity for a greedy and a seeded
    sampling request decoded CONCURRENTLY in one slot batch."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=3, max_len=64)
    prompt = np.array([5, 6, 7, 8, 9], np.int32)
    cases = [
        SamplingParams(max_new_tokens=8, seed=3),
        SamplingParams(max_new_tokens=8, temperature=1.0, top_k=5, seed=3),
        SamplingParams(max_new_tokens=6, temperature=0.7, top_k=13,
                       seed=11),
    ]
    handles = [eng.submit(prompt, sp) for sp in cases]
    eng.run_until_idle()
    for h, sp in zip(handles, cases):
        assert h.done and h.finish_reason in ("eos", "length")
        assert h.output_ids == solo_tokens(params, cfg, prompt, sp), sp


def test_slot_reuse_and_seed_reproducibility(model):
    """More requests than slots: retired slots are reused and every
    request still matches its solo run — including two identical
    (prompt, seed) requests submitted amid different co-batched traffic,
    which must produce identical tokens regardless of slot placement."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64, max_queue=16)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(2, cfg.vocab_size, (3 + i,)).astype(np.int32)
               for i in range(5)]
    sps = [SamplingParams(max_new_tokens=4 + i, seed=i,
                          temperature=0.5 * (i % 2), top_k=7 if i % 2
                          else None)
           for i in range(5)]
    twin = (np.array([4, 4, 4], np.int32),
            SamplingParams(max_new_tokens=5, temperature=1.0, top_k=9,
                           seed=42))
    handles = [eng.submit(p, sp) for p, sp in zip(prompts, sps)]
    h_twin1 = eng.submit(twin[0], twin[1])
    eng.run_until_idle()
    # resubmit the twin amid fresh traffic: different slot history, same
    # tokens
    h_more = [eng.submit(p, sp) for p, sp in zip(prompts[:2], sps[:2])]
    h_twin2 = eng.submit(twin[0], twin[1])
    eng.run_until_idle()
    for h, p, sp in zip(handles + h_more, list(prompts) + prompts[:2],
                        sps + sps[:2]):
        assert h.output_ids == solo_tokens(params, cfg, p, sp)
    assert h_twin1.output_ids == h_twin2.output_ids
    assert h_twin1.output_ids == solo_tokens(params, cfg, *twin)
    assert eng.scheduler.n_active == 0 and len(eng.queue) == 0


def test_midstream_admission_does_not_perturb_inflight(model):
    """Admitting B while A is mid-decode must not change A's tokens."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64)
    pa = np.array([9, 8, 7, 6], np.int32)
    pb = np.array([3, 4, 5], np.int32)
    sa = SamplingParams(max_new_tokens=10, seed=1, temperature=1.0,
                        top_k=11)
    ha = eng.submit(pa, sa)
    for _ in range(3):                       # A decodes alone for a while
        assert eng.step()
    assert not ha.done
    hb = eng.submit(pb, SamplingParams(max_new_tokens=6, seed=2))
    eng.run_until_idle()
    assert ha.output_ids == solo_tokens(params, cfg, pa, sa)
    assert hb.output_ids == solo_tokens(params, cfg, pb, hb.params)


def test_queue_backpressure_reject(model):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64, max_queue=2)
    sp = SamplingParams(max_new_tokens=2)
    p = np.array([2, 3], np.int32)
    h1, h2 = eng.submit(p, sp), eng.submit(p, sp)
    with pytest.raises(QueueFullError):
        eng.submit(p, sp)                      # bounded queue: reject
    assert eng.requests_rejected == 1
    eng.run_until_idle()
    assert h1.done and h2.done
    eng.submit(p, sp)                          # space again after drain
    eng.run_until_idle()


def test_eos_and_max_token_retirement(model):
    cfg, params = model
    prompt = np.array([7, 7, 8], np.int32)
    probe = generate(params, cfg, prompt[None], max_new_tokens=1)
    t0 = int(np.asarray(probe)[0, -1])         # the first greedy token
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64)
    # greedy request whose eos IS its first sampled token: finishes at
    # admission with zero output tokens, reason 'eos', slot freed
    h_eos = eng.submit(prompt, SamplingParams(max_new_tokens=5, eos_id=t0))
    h_len = eng.submit(prompt, SamplingParams(max_new_tokens=4,
                                              ignore_eos=True))
    eng.run_until_idle()
    assert h_eos.finish_reason == "eos" and h_eos.output_ids == []
    assert h_len.finish_reason == "length" and len(h_len.output_ids) == 4
    assert eng.scheduler.n_active == 0


def test_finish_during_admission_does_not_strand_queue(model):
    """Every request finishes DURING admission (eos is its first sampled
    token): step() must keep refilling the freed slot from the queue in
    the same tick instead of reporting idle with requests still queued."""
    cfg, params = model
    prompt = np.array([7, 7, 8], np.int32)
    probe = generate(params, cfg, prompt[None], max_new_tokens=1)
    t0 = int(np.asarray(probe)[0, -1])
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64, max_queue=8)
    handles = [eng.submit(prompt, SamplingParams(max_new_tokens=5,
                                                 eos_id=t0))
               for _ in range(3)]
    eng.run_until_idle()
    for h in handles:
        assert h.done and h.finish_reason == "eos" and h.output_ids == []
    assert eng.scheduler.n_active == 0 and len(eng.queue) == 0


def test_engine_loop_death_fails_requests_instead_of_hanging(model):
    """A BATCH-WIDE exception escaping step() on the background thread
    (here: the decode program itself dying) must fail the in-flight AND
    queued requests — result() raises, shutdown() returns — not strand
    them forever. (Per-REQUEST faults like a raising on_token callback no
    longer reach this path: they are isolated — see
    test_serving_resilience.py.)"""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64, max_queue=8)

    def bad_decode(*a, **kw):
        raise RuntimeError("decode program died")

    eng._decode = bad_decode
    sp = SamplingParams(max_new_tokens=4, ignore_eos=True)
    p = np.array([2, 3, 4], np.int32)
    h_bad = eng.submit(p, sp)
    h_queued = eng.submit(p, sp)
    eng.start()
    with pytest.raises(RuntimeError, match="engine loop error"):
        h_bad.result(timeout=30)
    with pytest.raises(RuntimeError, match="engine loop error"):
        h_queued.result(timeout=30)
    assert h_bad.finish_reason == "error" and h_bad.error
    # a dead engine rejects new submissions instead of silently
    # enqueueing them into a loop that will never run again
    with pytest.raises(RuntimeError, match="engine is dead"):
        eng.submit(p, sp)
    eng.shutdown()                             # must not spin forever
    assert eng.scheduler.n_active == 0 and len(eng.queue) == 0


def test_top_k_over_compiled_capacity_rejected(model):
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64, max_top_k=8)
    with pytest.raises(ValueError, match="top_k"):
        eng.submit(np.array([2, 3], np.int32),
                   SamplingParams(max_new_tokens=2, top_k=9))


def test_terminal_bucket_warmed_when_max_len_not_multiple_of_64(model):
    """max_len=48: the clamped terminal bucket (48) must be in the warmup
    set, so a fully in-capacity prompt (40 tokens) never fires a
    bucket-miss recompile after freeze."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=48)
    assert 48 in eng.prompt_buckets()
    eng.warmup()
    h = eng.submit(np.full((40,), 5, np.int32),
                   SamplingParams(max_new_tokens=3, ignore_eos=True))
    eng.run_until_idle()
    assert len(h.output_ids) == 3
    assert eng.n_recompiles == 0


def test_streaming_and_callbacks():
    # byte-vocab config: ByteTokenizer ids run 0..256, so the module
    # fixture's vocab-96 model would make "abc" (bytes 97-99) an
    # out-of-vocab poison prompt — which submit now REJECTS (see
    # test_out_of_vocab_prompt_rejected in test_serving_resilience.py)
    from building_llm_from_scratch_tpu.data.tokenizers import ByteTokenizer

    tok = ByteTokenizer()
    cfg = tiny_cfg(vocab_size=tok.vocab_size)
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = DecodeEngine(cfg, params, tokenizer=tok, n_slots=1, max_len=64)
    seen = []
    h = eng.submit("abc", SamplingParams(max_new_tokens=5,
                                         ignore_eos=True),
                   on_token=lambda r, t, piece: seen.append((t, piece)))
    eng.run_until_idle()
    pieces = list(h.stream(timeout=1))
    assert len(h.output_ids) == 5
    assert len(seen) == 5
    assert [t for t, _ in seen] == h.output_ids
    assert "".join(pieces) == h.text
    assert h.text == tok.decode(h.output_ids)


def test_incremental_detok_holds_partial_multibyte(model):
    """A token that is the first byte of a multi-byte UTF-8 char must be
    held (empty piece), then emitted as ONE complete char when the
    continuation byte arrives — not committed as a mangled replacement
    char; final flush emits whatever is left."""
    cfg, params = model
    from building_llm_from_scratch_tpu.data.tokenizers import ByteTokenizer

    eng = DecodeEngine(cfg, params, tokenizer=ByteTokenizer(), n_slots=1,
                       max_len=64)
    req = Request(9001, np.array([1], np.int32), SamplingParams())
    req.output_ids.append(0xC3)                # first byte of 'é'
    assert eng._detok_piece(req) == "" and req.text == ""
    req.output_ids.append(0xA9)                # continuation byte
    assert eng._detok_piece(req) == "é" and req.text == "é"
    req.output_ids.append(ord("x"))
    assert eng._detok_piece(req) == "x"
    req.output_ids.append(0xC3)                # dangling partial at finish
    assert eng._detok_piece(req) == ""
    assert eng._detok_piece(req, final=True) == "�"
    assert req.text == "éx�"
    assert req.text == ByteTokenizer().decode(req.output_ids[:-1]) + "�"


def test_zero_recompiles_after_warmup_and_bucket_miss_surfaces(model,
                                                               tmp_path):
    """The compile discipline the smoke gate enforces: warmup compiles the
    bucket set, in-bucket traffic never recompiles, and an out-of-bucket
    prompt fires a ``recompile`` event (the bucket-miss detector)."""
    from building_llm_from_scratch_tpu.obs.metrics import configure_metrics

    cfg = tiny_cfg(ctx=192)
    params = init_params(cfg, jax.random.PRNGKey(0))
    mj = str(tmp_path / "serve_metrics.jsonl")
    sink = configure_metrics(mj)
    sink.write_header(test="recompile")
    try:
        eng = DecodeEngine(cfg, params, n_slots=2, max_len=192,
                           warmup_prompt_cap=64)
        eng.warmup()
        assert eng.warmed_up
        # in-bucket traffic (prompt bucket 64): silent steady state
        h = eng.submit(np.arange(2, 12, dtype=np.int32),
                       SamplingParams(max_new_tokens=3, ignore_eos=True))
        eng.run_until_idle()
        assert len(h.output_ids) == 3
        assert eng.n_recompiles == 0
        # a 70-token prompt needs the UNWARMED 128 bucket: recompile event
        h2 = eng.submit(np.full((70,), 5, np.int32),
                        SamplingParams(max_new_tokens=2, ignore_eos=True))
        eng.run_until_idle()
        assert len(h2.output_ids) == 2
        assert eng.n_recompiles == 1
    finally:
        sink.close()
        configure_metrics(None)
    rows = [json.loads(line) for line in open(mj)]
    recompiles = [r for r in rows if r.get("event") == "recompile"]
    assert len(recompiles) == 1
    assert recompiles[0]["label"] == "serve_prefill"
    assert [r for r in rows if r.get("event") == "request_done"]
    assert [r for r in rows if r.get("event") == "serve_warmup"]


def test_http_frontend_generate_and_healthz(model):
    cfg, params = model
    from building_llm_from_scratch_tpu.serving.frontend import (
        make_http_server,
    )

    eng = DecodeEngine(cfg, params, n_slots=1, max_len=64)
    eng.start()
    server = make_http_server(eng, 0, host="127.0.0.1")
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["slots"] == 1 and health["queue_capacity"] >= 1
        body = json.dumps({"prompt_ids": [5, 6, 7], "max_new_tokens": 3,
                           "ignore_eos": True, "seed": 4})
        conn.request("POST", "/generate", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200, out
        assert len(out["token_ids"]) == 3
        assert out["finish_reason"] == "length"
        conn.close()
    finally:
        server.shutdown()
        server.server_close()
        eng.shutdown()


# ---------------------------------------------------------------------------
# scheduler / queue units (no jax)
# ---------------------------------------------------------------------------

def _dummy_req(i):
    return Request(1000 + i, np.array([1], np.int32), SamplingParams())


def test_scheduler_fcfs_admission_and_slot_reuse():
    q = RequestQueue(8)
    sched = Scheduler(2)
    reqs = [_dummy_req(i) for i in range(4)]
    for r in reqs:
        q.put(r)
    admitted = sched.admit_from(q)
    assert [(s, r.id) for s, r in admitted] == [(0, 1000), (1, 1001)]
    assert sched.n_active == 2 and sched.admit_from(q) == []
    sched.retire(0)
    # freed slot refills FCFS from the queue head
    assert [(s, r.id) for s, r in sched.admit_from(q)] == [(0, 1002)]
    with pytest.raises(ValueError):
        sched.retire(1) or sched.retire(1)
    sched.retire(0)
    assert [(s, r.id) for s, r in sched.admit_from(q)] == [(0, 1003)]
    assert sched.occupancy() == 0.5            # 1003 alone; 1001 retired


def test_request_queue_block_timeout_and_capacity():
    q = RequestQueue(1)
    q.put(_dummy_req(0))
    with pytest.raises(QueueFullError):
        q.put(_dummy_req(1))
    with pytest.raises(QueueFullError):
        q.put(_dummy_req(1), block=True, timeout=0.05)
    assert q.get_nowait().id == 1000
    q.put(_dummy_req(2))                      # capacity restored
    assert len(q) == 1
