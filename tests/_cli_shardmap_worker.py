"""Worker for the sp/pp CLI e2e tests (spawned by test_cli.py — not
collected by pytest).

These two e2e runs exercise shard_map collectives (ring ppermute / pipeline
schedule) on the virtual CPU mesh; the CPU collective runtime has been
observed to abort the interpreter under thread contention (rare,
non-deterministic). Running them in a child process keeps an abort out of
the suite process and lets the parent retry once.
"""

import json
import os
import sys

# Few virtual devices on purpose. XLA CPU's thunk executor runs
# independent collectives concurrently and different replicas can enter
# them in different orders; on this 1-core host that intermittently
# deadlocks the rendezvous until its 40s timeout aborts the process
# ("Termination timeout ... Exiting to ensure a consistent program
# state"). With a single collective-group family (sp: data=1 x seq=2;
# pp: data=2 x stage=2) the cross-group deadlock cannot form. The full
# dp x sp / dp x stage compositions are covered by the in-process parity
# tests (test_ring_attention.py / test_pipeline.py).
_N_DEV = {"sp": 2, "pp": 4, "pp_tp": 4}.get(
    sys.argv[1] if len(sys.argv) > 1 else "", 4)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    .replace("--xla_force_host_platform_device_count=8", "").strip()
    + f" --xla_force_host_platform_device_count={_N_DEV}").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    mode, data_dir, out = sys.argv[1], sys.argv[2], sys.argv[3]
    from building_llm_from_scratch_tpu.args import get_args
    from building_llm_from_scratch_tpu.main import main as run_main

    base = [
        "--data_dir", data_dir, "--output_dir", out,
        "--debug", "--byte_tokenizer", "--n_epochs", "1",
        "--batch_size", "8", "--eval_freq", "20",
        "--print_sample_iter", "10000", "--save_ckpt_freq", "10000",
        "--warmup_steps", "2", "--run_type", "multi_chip",
        "--model", "llama3_2", "--num_params", "1B",
    ]
    if mode == "sp":
        args = get_args(base + ["--sp", "2"])
        trainer = run_main(args)
        assert trainer.plan.n_seq == 2
        wq = trainer.state["trainable"]["blocks"]["attn"]["wq"]
        assert len(wq.sharding.device_set) == 2   # (data=1, seq=2)
    elif mode == "pp":
        args = get_args(base + ["--shard_mode", "pp", "--pp", "2",
                                "--pp_micro", "2"])
        trainer = run_main(args)
        assert trainer.plan.shard_mode == "pp"
        assert trainer.plan.n_stages == 2
        wq = trainer.state["trainable"]["blocks"]["attn"]["wq"]
        assert len(wq.sharding.device_set) == 4  # (data=2, stage=2)
    elif mode == "pp_tp":
        # pipeline x Megatron tp from the CLI (round-5 VERDICT #6):
        # (data=1, stage=2, model=2) on 4 virtual devices. NOTE: this
        # mode interleaves TWO collective families (stage ppermute +
        # per-layer model psums) — it relies on the parent's retry loop
        # if the rare CPU-runtime rendezvous abort ever hits it, unlike
        # sp/pp whose device counts keep a single family.
        args = get_args(base + ["--shard_mode", "pp", "--pp", "2",
                                "--tp", "2", "--pp_micro", "2"])
        trainer = run_main(args)
        assert trainer.plan.shard_mode == "pp"
        assert trainer.plan.n_stages == 2 and trainer.plan.n_tp == 2
        wq = trainer.state["trainable"]["blocks"]["attn"]["wq"]
        # really tp-sharded: model axis in the spec AND a halved local
        # shard on the head axis (device_set alone cannot tell sharded
        # from replicated on this mesh)
        assert "model" in str(wq.sharding.spec), wq.sharding.spec
        assert wq.addressable_shards[0].data.shape[-1] == wq.shape[-1] // 2
        assert len(wq.sharding.device_set) == 4  # stage x model
    else:
        raise SystemExit(f"unknown mode {mode}")
    assert trainer.global_step > 0
    assert np.isfinite(trainer.train_losses).all()
    print(f"WORKER_{mode.upper()}_OK", flush=True)


if __name__ == "__main__":
    main()
