"""Multi-tenant LoRA serving tests (serving/adapters.py + the model/ops
adapter path):

  - merge-free ``apply_lora`` parity against ``merge_lora`` (forward
    logits + generate() token equality) — the shared unmerged helper;
  - adapter artifact round-trip (rank/alpha/fingerprint) and the
    registry's refusal modes (fingerprint mismatch, capacity, rank,
    tree shape);
  - batched per-slot application: engine tokens bit-identical to
    single-adapter merged-weights ``generate()`` per adapter, mixed
    co-residency isolation (slot A's adapter never leaks into slot B),
    hot-load/evict under live traffic, zero recompiles throughout
    (frozen CompileWatcher);
  - per-adapter telemetry (request_done fields, labeled /metrics
    series) and the BGMV pallas kernel (interpret-mode parity on CPU,
    real-kernel parity TPU-gated).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import generate
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.models.lora import (
    adapter_fingerprint,
    apply_lora,
    count_lora_params,
    init_lora_params,
    load_adapter,
    merge_lora,
    save_adapter,
)
from building_llm_from_scratch_tpu.serving import (
    AdapterMismatchError,
    AdapterRegistry,
    AdapterRegistryFullError,
    DecodeEngine,
    SamplingParams,
)


def tiny_cfg(ctx=64, **kw):
    base = dict(name="lora-serve-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


def make_lora(cfg, params, seed, rank):
    """An adapter with NONZERO B (init_lora_params zeros B — its delta
    would be trivially zero and every parity test vacuous)."""
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(seed),
                            rank=rank)
    return jax.tree_util.tree_map(
        lambda a: a + 0.05 * jax.random.normal(
            jax.random.PRNGKey(seed + 1000), a.shape, a.dtype), lora)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture()
def registry(model, tmp_path):
    """Registry with adapters 'a' (rank 4), 'b' (rank 8) and 'c' (rank 2)
    loaded from real artifacts, one spare row; returns (registry,
    {name: (lora, rank, alpha)})."""
    cfg, params = model
    specs, loras = {}, {}
    for i, (name, rank, alpha) in enumerate([("a", 4, 8.0),
                                             ("b", 8, 16.0),
                                             ("c", 2, 3.0)]):
        lora = make_lora(cfg, params, 10 + i, rank)
        path = str(tmp_path / f"{name}.npz")
        save_adapter(path, lora, rank=rank, alpha=alpha, cfg=cfg)
        specs[name] = path
        loras[name] = (lora, rank, alpha)
    return AdapterRegistry.from_artifacts(cfg, params, specs,
                                          capacity=5), loras


def solo_tokens(ref_params, cfg, prompt, sp: SamplingParams):
    out, n = generate(ref_params, cfg, np.asarray(prompt)[None],
                      max_new_tokens=sp.max_new_tokens,
                      temperature=sp.temperature, top_k=sp.top_k,
                      eos_id=(None if sp.ignore_eos else cfg.eos_id),
                      rng=jax.random.PRNGKey(sp.seed),
                      return_n_generated=True)
    Tp = len(prompt)
    return [int(t) for t in out[0, Tp: Tp + int(n[0])]]


def merged_for(model, loras, name):
    cfg, params = model
    if name is None:
        return params
    lora, rank, alpha = loras[name]
    return merge_lora(params, lora, alpha, rank)


# ---------------------------------------------------------------------------
# apply_lora: the shared merge-free helper
# ---------------------------------------------------------------------------

def test_apply_lora_matches_merge_lora_projection():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 3, 16)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((16, 24)).astype(np.float32))
    a = jnp.asarray(rng.standard_normal((16, 4)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((4, 24)).astype(np.float32))
    scaling = 2.0
    got = apply_lora(x, w, {"A": a, "B": b}, scaling)
    want = x @ (w + scaling * a @ b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # node None is bit-identical to the bare matmul (base-path guarantee)
    np.testing.assert_array_equal(np.asarray(apply_lora(x, w, None)),
                                  np.asarray(x @ w))
    # per-row scale 0 = exact zero delta even with nonzero A/B
    batched = {"A": jnp.stack([a, a]), "B": jnp.stack([b, b])}
    got0 = apply_lora(x, w, batched, jnp.asarray([0.0, 2.0]))
    np.testing.assert_array_equal(np.asarray(got0[0]), np.asarray(x @ w)[0])


def test_unmerged_forward_and_generate_match_merged(model):
    from building_llm_from_scratch_tpu.models.transformer import forward

    cfg, params = model
    rank, alpha = 4, 8.0
    lora = make_lora(cfg, params, 7, rank)
    merged = merge_lora(params, lora, alpha, rank)
    toks = (np.arange(12, dtype=np.int32)[None, :] % 90)
    lm = forward(merged, cfg, jnp.asarray(toks))
    lu = forward(params, cfg, jnp.asarray(toks), lora=lora,
                 lora_scaling=alpha / rank)
    np.testing.assert_allclose(np.asarray(lm), np.asarray(lu),
                               rtol=2e-5, atol=2e-5)
    om = generate(merged, cfg, toks, max_new_tokens=12, eos_id=None,
                  rng=jax.random.PRNGKey(3))
    ou = generate(params, cfg, toks, max_new_tokens=12, eos_id=None,
                  rng=jax.random.PRNGKey(3), lora=lora, lora_alpha=alpha,
                  lora_rank=rank)
    np.testing.assert_array_equal(om, ou)


def test_generate_lora_requires_alpha_rank(model):
    cfg, params = model
    lora = make_lora(cfg, params, 7, 4)
    with pytest.raises(ValueError, match="lora_alpha"):
        generate(params, cfg, np.zeros((1, 4), np.int32),
                 max_new_tokens=2, lora=lora)


def test_count_lora_params(model):
    cfg, params = model
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(0), rank=2)
    expect = sum(int(np.prod(np.shape(leaf)))
                 for leaf in jax.tree_util.tree_leaves(lora))
    assert count_lora_params(lora) == expect > 0


# ---------------------------------------------------------------------------
# adapter artifacts + registry
# ---------------------------------------------------------------------------

def test_adapter_artifact_roundtrip(model, tmp_path):
    cfg, params = model
    lora = make_lora(cfg, params, 3, 4)
    path = str(tmp_path / "adap.npz")
    save_adapter(path, lora, rank=4, alpha=8.0, cfg=cfg)
    got, meta = load_adapter(path)
    assert meta["rank"] == 4 and meta["alpha"] == 8.0
    assert meta["fingerprint"] == adapter_fingerprint(cfg)
    for a, b in zip(jax.tree_util.tree_leaves(lora),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_rejects_non_adapter_npz(model, tmp_path):
    cfg, params = model
    path = str(tmp_path / "not_adapter.npz")
    np.savez(path, foo=np.zeros(3))
    reg = AdapterRegistry(cfg, params, capacity=2, max_rank=8)
    with pytest.raises(ValueError, match="not an adapter artifact"):
        reg.load("x", path)


def test_registry_refuses_fingerprint_mismatch(model, tmp_path):
    cfg, params = model
    other_cfg = tiny_cfg(emb_dim=48, n_heads=3)
    other_params = init_params(other_cfg, jax.random.PRNGKey(1))
    lora = make_lora(other_cfg, other_params, 5, 4)
    path = str(tmp_path / "mismatch.npz")
    save_adapter(path, lora, rank=4, alpha=8.0, cfg=other_cfg)
    reg = AdapterRegistry(cfg, params, capacity=2, max_rank=8)
    with pytest.raises(AdapterMismatchError):
        reg.load("bad", path)
    assert reg.n_loaded == 0


def test_registry_capacity_rank_and_duplicates(model, tmp_path):
    cfg, params = model
    paths = {}
    for name, rank in [("r1", 2), ("r2", 2), ("big", 16)]:
        p = str(tmp_path / f"{name}.npz")
        save_adapter(p, make_lora(cfg, params, hash(name) % 100, rank),
                     rank=rank, alpha=4.0, cfg=cfg)
        paths[name] = p
    reg = AdapterRegistry(cfg, params, capacity=2, max_rank=8)
    assert reg.load("r1", paths["r1"]) == 0
    with pytest.raises(ValueError, match="already loaded"):
        reg.load("r1", paths["r1"])
    with pytest.raises(ValueError, match="max_rank"):
        reg.load("big", paths["big"])
    assert reg.load("r2", paths["r2"]) == 1
    with pytest.raises(AdapterRegistryFullError):
        reg.load("r3", paths["r1"])
    # names flow into /metrics label values: quotes/braces/spaces refused
    for bad in ('ten"ant', "a b", "x{y}", "", "-lead"):
        with pytest.raises(ValueError, match="invalid"):
            reg.load(bad, paths["r1"])
    with pytest.raises(KeyError):
        reg.evict("nope")
    assert reg.evict("r1") == 0
    assert reg.lookup("r1") is None and reg.lookup("r2") == 1
    # freed row is reusable (no engine attached -> nothing in use)
    assert reg.load("r1b", paths["r1"]) == 0


# ---------------------------------------------------------------------------
# engine: batched per-slot application
# ---------------------------------------------------------------------------

def test_engine_adapter_parity_vs_merged_generate(model, registry):
    """Acceptance: mixed-adapter traffic (2 adapters + base interleaved),
    greedy AND seeded sampling — every request's tokens bit-identical to
    single-adapter merged-weights generate(), zero recompiles."""
    cfg, params = model
    reg, loras = registry
    engine = DecodeEngine(cfg, params, n_slots=4, max_len=64,
                          warmup_prompt_cap=32, adapters=reg)
    engine.warmup()
    rng = np.random.default_rng(0)
    cases = []
    for i, name in enumerate([None, "a", "b", "c", "a", None, "b", "c"]):
        prompt = rng.integers(0, 90, (4 + i % 5,)).astype(np.int32)
        sp = SamplingParams(
            max_new_tokens=6 + i % 4, ignore_eos=True, seed=i,
            temperature=0.8 if i % 2 else 0.0,
            top_k=8 if i % 2 else None, adapter=name)
        cases.append((engine.submit(prompt, sp), prompt, sp, name))
    engine.run_until_idle()
    for handle, prompt, sp, name in cases:
        handle.result(timeout=30)
        expect = solo_tokens(merged_for(model, loras, name), cfg, prompt,
                             sp)
        assert handle.output_ids == expect, (name, sp.seed)
    assert engine.n_recompiles == 0
    engine.shutdown()


def test_coresident_adapters_do_not_leak(model, registry):
    """Isolation: a request's tokens are identical whether it runs alone
    or co-batched with OTHER adapters' traffic — slot A's adapter never
    contaminates slot B."""
    cfg, params = model
    reg, _ = registry
    prompt = np.arange(5, dtype=np.int32) + 3
    sp = SamplingParams(max_new_tokens=8, ignore_eos=True, seed=42)

    def run(co_traffic: bool):
        engine = DecodeEngine(cfg, params, n_slots=4, max_len=64,
                              warmup_prompt_cap=32, adapters=reg)
        engine.warmup()
        main_req = engine.submit(prompt, sp)
        if co_traffic:
            rng = np.random.default_rng(9)
            for i, nm in enumerate(["a", "b", "a"]):
                engine.submit(rng.integers(0, 90, (6,)).astype(np.int32),
                              SamplingParams(max_new_tokens=10,
                                             ignore_eos=True, seed=50 + i,
                                             adapter=nm))
        engine.run_until_idle()
        main_req.result(timeout=30)
        engine.shutdown()
        return main_req.output_ids

    assert run(co_traffic=False) == run(co_traffic=True)


def test_hot_load_evict_under_traffic(model, registry, tmp_path):
    """Acceptance: hot-load and evict complete under live traffic (engine
    loop running) without failing in-flight requests, with zero
    recompiles."""
    cfg, params = model
    reg, loras = registry
    engine = DecodeEngine(cfg, params, n_slots=2, max_len=64,
                          warmup_prompt_cap=32, max_queue=64, adapters=reg)
    engine.warmup()
    engine.start()
    try:
        rng = np.random.default_rng(1)
        handles = []
        for i in range(10):       # steady 'a'/base traffic
            nm = "a" if i % 2 else None
            handles.append((nm, engine.submit(
                rng.integers(0, 90, (5,)).astype(np.int32),
                SamplingParams(max_new_tokens=12, ignore_eos=True,
                               seed=i, adapter=nm))))
        # hot-load 'hot' mid-traffic into the spare row, serve with it
        lora_c = make_lora(cfg, params, 77, 4)
        path_c = str(tmp_path / "hot.npz")
        save_adapter(path_c, lora_c, rank=4, alpha=8.0, cfg=cfg)
        reg.load("hot", path_c)
        c_prompt = rng.integers(0, 90, (5,)).astype(np.int32)
        c_sp = SamplingParams(max_new_tokens=8, ignore_eos=True, seed=99,
                              adapter="hot")
        c_handle = engine.submit(c_prompt, c_sp)
        # evict 'b' (no traffic) under load; in-flight work is untouched
        reg.evict("b")
        for nm, h in handles:
            h.result(timeout=60)
            assert h.finish_reason == "length", (nm, h.error)
        c_handle.result(timeout=60)
        merged_c = merge_lora(params, lora_c, 8.0, 4)
        assert c_handle.output_ids == solo_tokens(merged_c, cfg, c_prompt,
                                                  c_sp)
        # post-evict submits for 'b' reject at submit (HTTP 400 class)
        with pytest.raises(ValueError, match="not loaded"):
            engine.submit(c_prompt, SamplingParams(adapter="b"))
        assert engine.n_recompiles == 0
    finally:
        engine.shutdown()


def test_evicted_while_queued_fails_in_isolation(model, registry):
    """A queued request whose adapter is evicted before admission fails
    ALONE (reason adapter_not_loaded); co-queued base traffic decodes."""
    cfg, params = model
    reg, _ = registry
    engine = DecodeEngine(cfg, params, n_slots=1, max_len=64,
                          warmup_prompt_cap=32, max_queue=8, adapters=reg)
    engine.warmup()
    prompt = np.arange(4, dtype=np.int32) + 2
    doomed = engine.submit(prompt, SamplingParams(
        max_new_tokens=4, ignore_eos=True, adapter="a"))
    survivor = engine.submit(prompt, SamplingParams(
        max_new_tokens=4, ignore_eos=True))
    reg.evict("a")                # before any tick ran
    engine.run_until_idle()
    with pytest.raises(RuntimeError, match="evicted while queued"):
        doomed.result(timeout=10)
    survivor.result(timeout=10)
    assert survivor.finish_reason == "length"
    assert engine.n_recompiles == 0
    engine.shutdown()


def test_row_in_use_not_reused(model, registry, tmp_path):
    """An evicted adapter's pool row must not be overwritten while an
    active slot still decodes against it."""
    cfg, params = model
    reg, _ = registry   # capacity 5: rows 0-2 = 'a'/'b'/'c', rows 3-4 free
    engine = DecodeEngine(cfg, params, n_slots=1, max_len=64,
                          warmup_prompt_cap=32, adapters=reg)
    engine.warmup()
    prompt = np.arange(4, dtype=np.int32) + 2
    h = engine.submit(prompt, SamplingParams(max_new_tokens=50,
                                             ignore_eos=True, adapter="a"))
    assert engine.step()          # admitted: slot 0 references row 0
    reg.evict("a")
    # fill the two genuinely free rows (3, 4); row 0 must stay untouchable
    paths = {}
    for i, name in enumerate(["x1", "x2"]):
        p = str(tmp_path / f"{name}.npz")
        save_adapter(p, make_lora(cfg, params, 200 + i, 2), rank=2,
                     alpha=4.0, cfg=cfg)
        paths[name] = p
        row = reg.load(name, p)
        assert row != 0, "reused a row an active slot references"
    with pytest.raises(AdapterRegistryFullError, match="referenced"):
        reg.load("x3", paths["x1"])
    engine.run_until_idle()       # request finishes, slot frees
    h.result(timeout=30)
    assert reg.load("x3", paths["x1"]) == 0   # now reusable
    assert engine.n_recompiles == 0
    engine.shutdown()


def test_per_adapter_telemetry(model, registry):
    """request_done carries the adapter name; /metrics exports labeled
    per-adapter counters; stats() aggregates per adapter."""
    from building_llm_from_scratch_tpu.obs.metrics import (
        configure_metrics,
        get_metrics,
    )

    cfg, params = model
    reg, _ = registry
    configure_metrics(None)
    engine = DecodeEngine(cfg, params, n_slots=2, max_len=64,
                          warmup_prompt_cap=32, adapters=reg)
    engine.warmup()
    rows = []
    orig_event = get_metrics().event

    def spy(kind, **fields):
        rows.append((kind, fields))
        return orig_event(kind, **fields)

    get_metrics().event = spy
    try:
        prompt = np.arange(5, dtype=np.int32) + 1
        for nm in ["a", None, "b", "a"]:
            engine.submit(prompt, SamplingParams(
                max_new_tokens=4, ignore_eos=True, adapter=nm))
        engine.run_until_idle()
    finally:
        get_metrics().event = orig_event
    done = [f for k, f in rows if k == "request_done"]
    assert sorted(f.get("adapter", "base") for f in done) == \
        ["a", "a", "b", "base"]
    stats = engine.stats()
    assert stats["per_adapter"]["a"]["finished"] == 2
    assert stats["per_adapter"]["base"]["tokens"] == 4
    text = engine.prometheus_text()
    assert 'bllm_serve_adapter_requests_finished_total{adapter="a"} 2' \
        in text
    assert "bllm_serve_adapters_loaded" in text
    engine.shutdown()


def test_registry_less_engine_signature_unchanged(model):
    """Without a registry the engine's compiled call signature (and
    behavior) is the historical one — adapters are pay-for-use."""
    cfg, params = model
    engine = DecodeEngine(cfg, params, n_slots=2, max_len=64,
                          warmup_prompt_cap=32)
    engine.warmup()
    prompt = np.arange(4, dtype=np.int32) + 2
    with pytest.raises(ValueError, match="no adapter registry"):
        engine.submit(prompt, SamplingParams(adapter="a"))
    h = engine.submit(prompt, SamplingParams(max_new_tokens=4,
                                             ignore_eos=True))
    engine.run_until_idle()
    h.result(timeout=10)
    assert engine.n_recompiles == 0
    engine.shutdown()


# ---------------------------------------------------------------------------
# BGMV kernel (ops/decode_step.py)
# ---------------------------------------------------------------------------

def _bgmv_case():
    rng = np.random.default_rng(0)
    S, N, D, r, O = 5, 3, 128, 8, 256
    x = rng.standard_normal((S, D)).astype(np.float32)
    A = rng.standard_normal((N, D, r)).astype(np.float32)
    B = rng.standard_normal((N, r, O)).astype(np.float32)
    ids = np.array([0, -1, 2, 1, 2], np.int32)
    scales = np.array([0.5, 2.0, 0.25], np.float32)
    ref = np.stack([
        (scales[i] * (x[s] @ A[i]) @ B[i]) if i >= 0
        else np.zeros(O, np.float32)
        for s, i in enumerate(ids)
    ])
    return x, A, B, ids, scales, ref


def test_lora_bgmv_interpret_parity():
    from building_llm_from_scratch_tpu.ops.decode_step import lora_bgmv

    x, A, B, ids, scales, ref = _bgmv_case()
    out = np.asarray(lora_bgmv(jnp.asarray(x), jnp.asarray(A),
                               jnp.asarray(B), jnp.asarray(ids),
                               jnp.asarray(scales), interpret=True))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="real pallas kernel needs a TPU")
def test_lora_bgmv_tpu_parity():
    from building_llm_from_scratch_tpu.ops.decode_step import lora_bgmv

    x, A, B, ids, scales, ref = _bgmv_case()
    out = np.asarray(lora_bgmv(jnp.asarray(x), jnp.asarray(A),
                               jnp.asarray(B), jnp.asarray(ids),
                               jnp.asarray(scales)))
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_supports_lora_shape_gate():
    from building_llm_from_scratch_tpu.ops.decode_step import (
        supports_lora_shape,
    )

    assert supports_lora_shape(768, 8, 768)
    assert supports_lora_shape(768, 16, 3072)
    assert not supports_lora_shape(100, 8, 768)      # unaligned in
    assert not supports_lora_shape(768, 8, 50257)    # unaligned out
    assert not supports_lora_shape(768, 4, 768)      # sub-sublane rank
