"""Attention implementation parity: flash/pallas vs the exact xla oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import forward, init_params
from building_llm_from_scratch_tpu.ops.attention import causal_attention


def _qkv(B=2, T=256, Hq=4, Hkv=2, D=16, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, Hq, D), dtype)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), dtype)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), dtype)
    return q, k, v


def test_flash_matches_xla_fp32():
    q, k, v = _qkv()
    want = causal_attention(q, k, v, impl="xla")
    got = causal_attention(q, k, v, impl="flash", block_q=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_flash_matches_xla_bf16():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    want = np.asarray(causal_attention(q, k, v, impl="xla"), np.float32)
    got = np.asarray(causal_attention(q, k, v, impl="flash", block_q=64),
                     np.float32)
    np.testing.assert_allclose(got, want, atol=1e-2, rtol=1e-2)


def test_flash_odd_lengths_fall_to_divisor_blocks():
    q, k, v = _qkv(T=192)                       # 192 % 256 != 0
    want = causal_attention(q, k, v, impl="xla")
    got = causal_attention(q, k, v, impl="flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_flash_gradients_match_xla():
    q, k, v = _qkv(T=128)

    def loss(impl, q, k, v):
        out = causal_attention(q, k, v, impl=impl, block_q=32)
        return jnp.sum(out * out)

    gw = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: loss("flash", *a), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_dropout_preserves_mean_and_causality():
    """Dropout path: output stays causal (position t only sees <= t) and the
    kept weights are rescaled (mean roughly preserved)."""
    q, k, v = _qkv(T=64)
    rng = jax.random.PRNGKey(3)
    out = causal_attention(q, k, v, impl="flash", block_q=16,
                           dropout_rate=0.5, dropout_rng=rng,
                           deterministic=False)
    assert np.isfinite(np.asarray(out)).all()
    # causality probe: changing future k/v must not affect position 0
    k2 = k.at[:, 32:].set(0.0)
    v2 = v.at[:, 32:].set(0.0)
    out2 = causal_attention(q, k2, v2, impl="flash", block_q=16,
                            dropout_rate=0.5, dropout_rng=rng,
                            deterministic=False)
    np.testing.assert_allclose(np.asarray(out[:, :32]),
                               np.asarray(out2[:, :32]), atol=1e-6)


def test_full_model_forward_flash_matches_xla():
    cfg = ModelConfig(
        name="t", vocab_size=128, context_length=256, emb_dim=64, n_heads=4,
        n_layers=2, hidden_dim=128, n_kv_groups=2, norm="rmsnorm",
        positional="rope", activation="swiglu", drop_rate=0.0, dtype="fp32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = np.arange(2 * 256, dtype=np.int32).reshape(2, 256) % 128
    want = np.asarray(forward(params, cfg.replace(attn_impl="xla"), toks))
    got = np.asarray(forward(params, cfg.replace(attn_impl="flash"), toks))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_auto_uses_xla_for_decode_shapes():
    """Cached decode (kv_length set) must stay on the exact xla path."""
    q, k, v = _qkv(T=8)
    out = causal_attention(q[:, :1], k, v,
                           q_positions=jnp.asarray([4]),
                           kv_length=jnp.asarray([5, 5]), impl="flash")
    want = causal_attention(q[:, :1], k, v,
                            q_positions=jnp.asarray([4]),
                            kv_length=jnp.asarray([5, 5]), impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=0)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="pallas flash kernel needs a real TPU")
def test_pallas_matches_xla_on_tpu():
    q, k, v = _qkv(T=512, D=64, dtype=jnp.bfloat16)
    want = np.asarray(causal_attention(q, k, v, impl="xla"), np.float32)
    got = np.asarray(causal_attention(q, k, v, impl="pallas"), np.float32)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="pallas flash kernel needs a real TPU")
def test_pallas_gradients_match_xla_on_tpu():
    """The tuned-block pallas path must be exact in the backward too (it
    feeds real training steps when auto picks it at seq >= 2048)."""
    q, k, v = _qkv(T=2048, Hq=4, Hkv=2, D=64, dtype=jnp.bfloat16)

    def loss(impl, q, k, v):
        out = causal_attention(q, k, v, impl=impl)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    gw = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(lambda *a: loss("pallas", *a), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gw):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=2e-1, rtol=5e-2)


def test_auto_selection_policy():
    """auto: xla for decode/q_positions; on TPU the in-house fused kernel
    (dropout included) owns block-divisible training shapes; flash covers
    CPU and odd shapes; xla otherwise."""
    from building_llm_from_scratch_tpu.ops.attention import _resolve_impl

    on_tpu = jax.default_backend() == "tpu"
    # decode / chunked-prefill shapes pin to the exact oracle
    assert _resolve_impl("auto", 1, 64, 64, None, jnp.asarray([5]), False,
                         256) == "xla"
    assert _resolve_impl("flash", 64, 64, 64, jnp.arange(64), None, False,
                         256) == "xla"
    assert _resolve_impl("pallas", 64, 64, 64, jnp.arange(64), None, False,
                         256) == "xla"
    # training shapes: fused on TPU (with or without dropout), flash on CPU
    expect_train = "fused" if on_tpu else "flash"
    assert _resolve_impl("auto", 1024, 1024, 64, None, None, False,
                         256) == expect_train
    assert _resolve_impl("auto", 2048, 2048, 64, None, None, False,
                         256) == expect_train
    assert _resolve_impl("auto", 2048, 2048, 64, None, None, True,
                         256) == expect_train
    # short sequences stay exact
    assert _resolve_impl("auto", 128, 128, 64, None, None, False,
                         256) == "xla"
