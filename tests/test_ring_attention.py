"""Ring attention (sequence parallelism) parity on the 8-device CPU mesh:
the ring schedule is placement, not semantics — outputs, gradients and
training losses must match the single-device oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.models import forward, init_params
from building_llm_from_scratch_tpu.ops.attention import causal_attention
from building_llm_from_scratch_tpu.ops.ring_attention import (
    ring_causal_attention,
)
from building_llm_from_scratch_tpu.parallel import build_mesh_plan
from building_llm_from_scratch_tpu.parallel.collectives import shard_map
from building_llm_from_scratch_tpu.training import (
    build_optimizer,
    init_train_state,
    make_train_step,
)


def _qkv(B=2, T=256, Hq=4, Hkv=2, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, Hq, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_xla_oracle(sp):
    plan = build_mesh_plan("dp", sp=sp)
    # batch must divide the data axis (8/sp devices)
    q, k, v = _qkv(B=8 // sp)
    want = causal_attention(q, k, v, impl="xla")
    got = jax.jit(lambda a, b, c: ring_causal_attention(a, b, c, plan.mesh))(
        q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_gradients_match_xla():
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(T=128)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    gw = jax.grad(lambda *a: loss(
        lambda x, y, z: causal_attention(x, y, z, impl="xla"), *a),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.jit(jax.grad(lambda *a: loss(
        lambda x, y, z: ring_causal_attention(x, y, z, plan.mesh), *a),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ring_rejects_indivisible_seq():
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(T=130)
    with pytest.raises(ValueError, match="not divisible"):
        ring_causal_attention(q, k, v, plan.mesh)


def _llama_cfg():
    # fp32 params: the ring path carries softmax weights in fp32 through the
    # PV accumulation while the xla oracle casts them to the value dtype
    # first, so under bf16 params the two differ by ~bf16-epsilon — parity
    # is asserted in fp32 where both are exact
    return get_config("llama3_2", "1B", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=512, context_length=128,
        drop_rate=0.0, dtype="fp32")


def test_sp_forward_matches_single_device():
    """Full-model forward with sp=4 == plain forward."""
    cfg = _llama_cfg()
    plan = build_mesh_plan("dp", sp=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = np.arange(2 * cfg.context_length, dtype=np.int32).reshape(2, -1) \
        % cfg.vocab_size
    want = forward(params, cfg, toks)
    sharded = plan.shard_params(params, copy=False)
    batch_toks = plan.shard_batch({"inputs": toks})["inputs"]
    got = jax.jit(lambda p, t: forward(p, cfg, t, sp_mesh=plan.mesh))(
        sharded, batch_toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_sp_training_matches_single_device():
    """3 sp=4 (dp=2 x seq=4) train steps == 3 single-device steps — the
    load-bearing sequence-parallelism parity case (round-2 VERDICT #8)."""
    cfg = _llama_cfg()
    opt = build_optimizer(peak_lr=1e-3, warmup_steps=2, total_steps=10)
    rng = np.random.default_rng(0)
    batches = []
    for s in range(3):
        x = rng.integers(0, cfg.vocab_size,
                         (8, cfg.context_length)).astype(np.int32)
        batches.append({"inputs": x, "targets": np.roll(x, -1, 1),
                        "weights": np.ones_like(x, np.float32)})

    ref_state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                                 opt, jax.random.PRNGKey(0))
    ref_step = make_train_step(cfg, opt)
    ref_losses = []
    for b in batches:
        ref_state, m = ref_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    plan = build_mesh_plan("dp", sp=4)
    assert plan.mesh.shape == {"data": 2, "seq": 4, "model": 1}
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                             opt, jax.random.PRNGKey(0))
    state = plan.shard_state(state)
    step = make_train_step(cfg, opt, sp_mesh=plan.sp_mesh)
    losses = []
    for b in batches:
        state, m = step(state, plan.shard_batch(b))
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)
    ref_w = np.asarray(ref_state["trainable"]["blocks"]["attn"]["wq"])
    got_w = np.asarray(
        jax.device_get(state["trainable"]["blocks"]["attn"]["wq"]))
    np.testing.assert_allclose(got_w, ref_w, rtol=2e-3, atol=2e-5)


def test_sp_with_fsdp_params():
    """sp composes with fsdp param sharding (data axis shards params AND
    batch rows; seq axis shards tokens)."""
    cfg = _llama_cfg()
    opt = build_optimizer(total_steps=10)
    plan = build_mesh_plan("fsdp", sp=4)
    state = plan.shard_state(init_train_state(
        init_params(cfg, jax.random.PRNGKey(0)), opt, jax.random.PRNGKey(0)))
    step = make_train_step(cfg, opt, sp_mesh=plan.sp_mesh)
    rng = np.random.default_rng(1)
    x = rng.integers(0, cfg.vocab_size,
                     (8, cfg.context_length)).astype(np.int32)
    batch = plan.shard_batch({"inputs": x, "targets": np.roll(x, -1, 1),
                              "weights": np.ones_like(x, np.float32)})
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# round-4 additions: ring attention dropout + bf16_hybrid sp composition
# (r3 VERDICT weakness #6 lifted)
# ---------------------------------------------------------------------------

def test_ring_dropout_deterministic_causal_and_rescaled():
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(T=256)
    rng = jax.random.PRNGKey(5)
    f = jax.jit(lambda q, k, v: ring_causal_attention(
        q, k, v, plan.mesh, dropout_rate=0.3, dropout_rng=rng))
    o1 = np.asarray(f(q, k, v))
    o2 = np.asarray(f(q, k, v))
    np.testing.assert_array_equal(o1, o2)           # deterministic per key
    assert np.isfinite(o1).all()
    # different key -> different masks
    o3 = np.asarray(jax.jit(lambda q, k, v: ring_causal_attention(
        q, k, v, plan.mesh, dropout_rate=0.3,
        dropout_rng=jax.random.PRNGKey(6)))(q, k, v))
    assert not np.array_equal(o1, o3)
    # causality: zeroing future kv leaves the first shard's outputs intact
    k2 = k.at[:, 64:].set(0.0)
    v2 = v.at[:, 64:].set(0.0)
    o4 = np.asarray(f(q, k2, v2))
    np.testing.assert_allclose(o1[:, :64], o4[:, :64], atol=1e-6)
    # kept weights are rescaled by 1/(1-p): position 0 attends only to
    # itself, so each head's output row 0 is either v[0]/0.7 or exactly 0
    row0 = o1[:, 0, :, :]                            # (B, Hq, D)
    v0 = np.asarray(v[:, 0, :, :])                   # (B, Hkv, D)
    v0 = np.repeat(v0, o1.shape[2] // v0.shape[1], axis=1) / 0.7
    kept = np.abs(row0) > 1e-8
    np.testing.assert_allclose(row0[kept],
                               np.broadcast_to(v0, row0.shape)[kept],
                               rtol=1e-5)


def test_ring_dropout_mean_preserving():
    """E[dropout(attn)] == attn: check the sample mean over many key draws
    approaches the no-dropout output."""
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(T=128)
    want = np.asarray(ring_causal_attention(q, k, v, plan.mesh))
    f = jax.jit(lambda r: ring_causal_attention(
        q, k, v, plan.mesh, dropout_rate=0.3, dropout_rng=r))
    acc = np.zeros_like(want)
    n = 32
    for i in range(n):
        acc += np.asarray(f(jax.random.PRNGKey(100 + i)))
    # a peaked softmax row keeps single-key Bernoulli variance however many
    # keys it attends, so elementwise bounds are noise-limited; assert the
    # aggregate statistics of the sample mean instead
    dev = np.abs(acc / n - want)
    assert dev.mean() < 0.05, dev.mean()
    assert np.quantile(dev, 0.999) < 0.5, np.quantile(dev, 0.999)


def test_ring_dropout_gradients_finite():
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(T=128)
    rng = jax.random.PRNGKey(7)

    def loss(q, k, v):
        o = ring_causal_attention(q, k, v, plan.mesh, dropout_rate=0.2,
                                  dropout_rng=rng)
        return jnp.sum(o ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for x in g:
        assert np.isfinite(np.asarray(x)).all()


def test_sp_composes_with_bf16_hybrid_step():
    """--sp 2 + --mixed_precision bf16_hybrid: the explicit-psum step maps
    the seq axis and matches the GSPMD step's loss exactly at dropout 0."""
    from building_llm_from_scratch_tpu.training import (
        get_policy,
        make_sharded_train_step,
    )

    cfg = _llama_cfg()
    opt = build_optimizer(total_steps=10)
    plan = build_mesh_plan("dp", sp=2)
    policy = get_policy("bf16_hybrid")

    params = init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(3)
    x = rng.integers(0, cfg.vocab_size,
                     (8, cfg.context_length)).astype(np.int32)
    batch = {"inputs": x, "targets": np.roll(x, -1, 1).astype(np.int32),
             "weights": np.ones_like(x, np.float32)}

    ref_state = init_train_state(params, opt, jax.random.PRNGKey(0),
                                 policy=policy)
    ref_step = make_train_step(cfg, opt, policy=policy)
    _, ref_m = ref_step(ref_state, batch)

    state = plan.shard_state(init_train_state(
        init_params(cfg, jax.random.PRNGKey(0)), opt, jax.random.PRNGKey(0),
        policy=policy))
    step = make_sharded_train_step(cfg, opt, plan, policy=policy)
    state, m = step(state, plan.shard_batch(batch))
    np.testing.assert_allclose(float(m["loss"]), float(ref_m["loss"]),
                               rtol=2e-4)
    # and it keeps training
    state, m2 = step(state, plan.shard_batch(batch))
    assert np.isfinite(float(m2["loss"]))


def test_sp_gpt2_dropout_training_runs():
    """GPT-2 (attention dropout 0.1) trains under sp — the r3 hard error is
    gone; losses stay finite and decrease on a repeated batch."""
    cfg = get_config("GPT2", "124M", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=256, context_length=64,
        n_heads=4, n_layers=2)
    assert cfg.drop_rate > 0.0
    opt = build_optimizer(total_steps=12)
    plan = build_mesh_plan("dp", sp=4)
    state = plan.shard_state(init_train_state(
        init_params(cfg, jax.random.PRNGKey(0)), opt, jax.random.PRNGKey(1)))
    step = make_train_step(cfg, opt, sp_mesh=plan.sp_mesh)
    rng = np.random.default_rng(0)
    x = rng.integers(0, cfg.vocab_size, (8, 64)).astype(np.int32)
    batch = plan.shard_batch({"inputs": x,
                              "targets": np.roll(x, -1, 1).astype(np.int32),
                              "weights": np.ones_like(x, np.float32)})
    losses = []
    for _ in range(10):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_sp_inside_forward_matches_global_forward():
    """forward_hidden under the seq-mapped shard_map (sp_inside) must equal
    the global forward ELEMENTWISE — this is the check that catches
    shard-local positional-encoding bugs a random-init loss comparison
    cannot (each seq shard must apply its global RoPE/pos-emb offsets)."""
    from jax.sharding import PartitionSpec as P

    from building_llm_from_scratch_tpu.models.transformer import (
        forward_hidden,
    )
    from building_llm_from_scratch_tpu.parallel.mesh import (
        DATA_AXIS,
        SEQ_AXIS,
    )

    for family in ("llama", "gpt2"):
        if family == "llama":
            cfg = _llama_cfg()
        else:
            cfg = get_config("GPT2", "124M", debug=True).replace(
                emb_dim=64, hidden_dim=128, vocab_size=256,
                context_length=128, n_heads=4, n_layers=2, drop_rate=0.0)
        plan = build_mesh_plan("dp", sp=2)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        toks = rng.integers(0, cfg.vocab_size,
                            (4, cfg.context_length)).astype(np.int32)

        want = np.asarray(forward_hidden(params, cfg, jnp.asarray(toks)))

        body = lambda p, t: forward_hidden(p, cfg, t,
                                           sp_inside=(SEQ_AXIS, 2))
        got = np.asarray(jax.jit(shard_map(
            body, mesh=plan.mesh,
            in_specs=(P(), P(DATA_AXIS, SEQ_AXIS)),
            out_specs=P(DATA_AXIS, SEQ_AXIS),
            check_vma=False))(params, jnp.asarray(toks)))
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5,
                                   err_msg=family)


# ---------------------------------------------------------------------------
# long-context tier additions (PR 20): odd per-shard pane sizes + bf16
# parity — the shard-size/dtype corners the 32k pretrain config lands on
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sp,T", [(2, 6), (4, 84), (2, 250)])
def test_ring_odd_shard_sizes_match_oracle(sp, T):
    """Per-shard panes that are odd or non-power-of-two (3, 21, 125
    tokens/device) match the dense oracle — the ring schedule has no
    hidden power-of-two or evenness assumption beyond T % sp == 0."""
    plan = build_mesh_plan("dp", sp=sp)
    q, k, v = _qkv(B=8 // sp, T=T)
    want = causal_attention(q, k, v, impl="xla")
    got = jax.jit(lambda a, b, c: ring_causal_attention(a, b, c, plan.mesh))(
        q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_bf16_matches_oracle(sp):
    """bf16 q/k/v through the ring: the fp32 online-softmax accumulator
    keeps the result within bf16 resolution of the dense oracle, and the
    output dtype stays bf16 (no silent fp32 widening into the residual
    stream)."""
    q, k, v = _qkv(B=8 // sp, T=128)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    plan = build_mesh_plan("dp", sp=sp)
    want = causal_attention(qb, kb, vb, impl="xla")
    got = jax.jit(lambda a, b, c: ring_causal_attention(a, b, c, plan.mesh))(
        qb, kb, vb)
    assert got.dtype == jnp.bfloat16
    assert want.dtype == jnp.bfloat16
    # the ring carries softmax weights in fp32 through the PV
    # accumulation while the oracle casts them to bf16 first — the two
    # agree to ~bf16 epsilon, not exactly (same bound _llama_cfg notes)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=3e-2, rtol=3e-2)


def test_ring_gradients_odd_shards():
    """Gradients through the ring at an odd per-shard pane (21
    tokens/device): the backward ppermute chain must handle the same
    shard sizes the forward does."""
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(B=2, T=84)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    gw = jax.grad(lambda *a: loss(
        lambda x, y, z: causal_attention(x, y, z, impl="xla"), *a),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.jit(jax.grad(lambda *a: loss(
        lambda x, y, z: ring_causal_attention(x, y, z, plan.mesh), *a),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)
