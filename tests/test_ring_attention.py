"""Ring attention (sequence parallelism) parity on the 8-device CPU mesh:
the ring schedule is placement, not semantics — outputs, gradients and
training losses must match the single-device oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.models import forward, init_params
from building_llm_from_scratch_tpu.ops.attention import causal_attention
from building_llm_from_scratch_tpu.ops.ring_attention import (
    ring_causal_attention,
)
from building_llm_from_scratch_tpu.parallel import build_mesh_plan
from building_llm_from_scratch_tpu.training import (
    build_optimizer,
    init_train_state,
    make_train_step,
)


def _qkv(B=2, T=256, Hq=4, Hkv=2, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, Hq, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_xla_oracle(sp):
    plan = build_mesh_plan("dp", sp=sp)
    # batch must divide the data axis (8/sp devices)
    q, k, v = _qkv(B=8 // sp)
    want = causal_attention(q, k, v, impl="xla")
    got = jax.jit(lambda a, b, c: ring_causal_attention(a, b, c, plan.mesh))(
        q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_gradients_match_xla():
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(T=128)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    gw = jax.grad(lambda *a: loss(
        lambda x, y, z: causal_attention(x, y, z, impl="xla"), *a),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.jit(jax.grad(lambda *a: loss(
        lambda x, y, z: ring_causal_attention(x, y, z, plan.mesh), *a),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gr, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ring_rejects_indivisible_seq():
    plan = build_mesh_plan("dp", sp=4)
    q, k, v = _qkv(T=130)
    with pytest.raises(ValueError, match="not divisible"):
        ring_causal_attention(q, k, v, plan.mesh)


def _llama_cfg():
    # fp32 params: the ring path carries softmax weights in fp32 through the
    # PV accumulation while the xla oracle casts them to the value dtype
    # first, so under bf16 params the two differ by ~bf16-epsilon — parity
    # is asserted in fp32 where both are exact
    return get_config("llama3_2", "1B", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=512, context_length=128,
        drop_rate=0.0, dtype="fp32")


def test_sp_forward_matches_single_device():
    """Full-model forward with sp=4 == plain forward."""
    cfg = _llama_cfg()
    plan = build_mesh_plan("dp", sp=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = np.arange(2 * cfg.context_length, dtype=np.int32).reshape(2, -1) \
        % cfg.vocab_size
    want = forward(params, cfg, toks)
    sharded = plan.shard_params(params, copy=False)
    batch_toks = plan.shard_batch({"inputs": toks})["inputs"]
    got = jax.jit(lambda p, t: forward(p, cfg, t, sp_mesh=plan.mesh))(
        sharded, batch_toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_sp_training_matches_single_device():
    """3 sp=4 (dp=2 x seq=4) train steps == 3 single-device steps — the
    load-bearing sequence-parallelism parity case (round-2 VERDICT #8)."""
    cfg = _llama_cfg()
    opt = build_optimizer(peak_lr=1e-3, warmup_steps=2, total_steps=10)
    rng = np.random.default_rng(0)
    batches = []
    for s in range(3):
        x = rng.integers(0, cfg.vocab_size,
                         (8, cfg.context_length)).astype(np.int32)
        batches.append({"inputs": x, "targets": np.roll(x, -1, 1),
                        "weights": np.ones_like(x, np.float32)})

    ref_state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                                 opt, jax.random.PRNGKey(0))
    ref_step = make_train_step(cfg, opt)
    ref_losses = []
    for b in batches:
        ref_state, m = ref_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    plan = build_mesh_plan("dp", sp=4)
    assert plan.mesh.shape == {"data": 2, "seq": 4, "model": 1}
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                             opt, jax.random.PRNGKey(0))
    state = plan.shard_state(state)
    step = make_train_step(cfg, opt, sp_mesh=plan.sp_mesh)
    losses = []
    for b in batches:
        state, m = step(state, plan.shard_batch(b))
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)
    ref_w = np.asarray(ref_state["trainable"]["blocks"]["attn"]["wq"])
    got_w = np.asarray(
        jax.device_get(state["trainable"]["blocks"]["attn"]["wq"]))
    np.testing.assert_allclose(got_w, ref_w, rtol=2e-3, atol=2e-5)


def test_sp_with_fsdp_params():
    """sp composes with fsdp param sharding (data axis shards params AND
    batch rows; seq axis shards tokens)."""
    cfg = _llama_cfg()
    opt = build_optimizer(total_steps=10)
    plan = build_mesh_plan("fsdp", sp=4)
    state = plan.shard_state(init_train_state(
        init_params(cfg, jax.random.PRNGKey(0)), opt, jax.random.PRNGKey(0)))
    step = make_train_step(cfg, opt, sp_mesh=plan.sp_mesh)
    rng = np.random.default_rng(1)
    x = rng.integers(0, cfg.vocab_size,
                     (8, cfg.context_length)).astype(np.int32)
    batch = plan.shard_batch({"inputs": x, "targets": np.roll(x, -1, 1),
                              "weights": np.ones_like(x, np.float32)})
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
