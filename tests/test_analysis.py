"""graft-lint tests: every GLxxx rule detected on its seeded fixture with
the right id/line, clean fixtures report zero, the repo itself gates
clean against the checked-in baseline, baselines round-trip, inline
suppressions work, and the runtime sanitizers (lock-order, transfer
sentry) catch what the static rules cannot.

The static passes are stdlib-only, so most of this file runs in
milliseconds; only the sanitizer integration tests touch jax.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from building_llm_from_scratch_tpu.analysis.base import (
    Finding,
    ParsedModule,
    RULES,
)
from building_llm_from_scratch_tpu.analysis.runner import (
    default_baseline_path,
    discover,
    main as lint_main,
    parse_modules,
    repo_root,
    run_checkers,
)
from building_llm_from_scratch_tpu.analysis.runtime import (
    ImplicitTransferError,
    LockOrderSanitizer,
    no_implicit_device_to_host,
)
from building_llm_from_scratch_tpu.obs import schema

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")


def lint_files(*names):
    root = repo_root()
    files = [os.path.join(FIXTURES, n) for n in names]
    return run_checkers(parse_modules(root, files))


def rules_of(findings):
    return sorted({f.rule for f in findings})


def at_line(findings, rule):
    return sorted(f.line for f in findings if f.rule == rule)


def fixture_line(name, needle):
    """1-indexed line of the first occurrence of ``needle``."""
    path = os.path.join(FIXTURES, name)
    for i, line in enumerate(open(path), start=1):
        if needle in line:
            return i
    raise AssertionError(f"{needle!r} not in {name}")


# ---------------------------------------------------------------------------
# per-rule fixture detection
# ---------------------------------------------------------------------------

def test_gl01_hostsync_fixture_detects_each_rule_at_its_line():
    findings = lint_files("viol_gl01.py")
    assert rules_of(findings) == ["GL011", "GL012", "GL013"]
    assert at_line(findings, "GL011") == [
        fixture_line("viol_gl01.py", "float(step_out)")]
    assert at_line(findings, "GL012") == [
        fixture_line("viol_gl01.py", "np.asarray(device_value)"),
        fixture_line("viol_gl01.py", "device_value.tolist()")]
    assert at_line(findings, "GL013") == [
        fixture_line("viol_gl01.py", "device_value.item()")]
    # the suppressed int() and the cold path produced nothing
    assert not any("cold_path" in f.qualname for f in findings)
    # every finding carries the enclosing qualname + a fingerprint
    for f in findings:
        assert f.qualname == "hot_loop"
        assert len(f.fingerprint) == 16


def test_gl02_jitpurity_fixture_detects_each_rule():
    findings = lint_files("viol_gl02.py")
    assert rules_of(findings) == ["GL021", "GL022", "GL023", "GL024",
                                  "GL025", "GL026"]
    assert at_line(findings, "GL021") == [
        fixture_line("viol_gl02.py", 'print("tracing')]
    assert at_line(findings, "GL022") == [
        fixture_line("viol_gl02.py", "time.perf_counter()")]
    assert at_line(findings, "GL023") == [
        fixture_line("viol_gl02.py", "random.random()")]
    assert at_line(findings, "GL024") == [
        fixture_line("viol_gl02.py", "if flag:")]
    assert at_line(findings, "GL025") == [
        fixture_line("viol_gl02.py", "self.last_x = x")]
    assert at_line(findings, "GL026") == [
        fixture_line("viol_gl02.py", "fwd = jax.jit(lambda")]


def test_gl03_locks_fixture_detects_unguarded_access_and_cycle():
    findings = lint_files("viol_gl03.py")
    assert rules_of(findings) == ["GL031", "GL032", "GL033"]
    # the unguarded write AND the unguarded read; the with-lock access,
    # the `# holds:`-annotated helper and the suppressed read are clean
    assert at_line(findings, "GL031") == [
        fixture_line("viol_gl03.py", "# line 21: GL031"),
        fixture_line("viol_gl03.py", "# line 24: GL031")]
    # annotation naming a lock the class never defines
    assert at_line(findings, "GL033") == [
        fixture_line("viol_gl03.py", "guarded-by: _no_such_lock")]
    # the AB/BA call graph closes a lock cycle
    cycles = [f for f in findings if f.rule == "GL032"]
    assert len(cycles) == 1
    assert "lock_a" in cycles[0].message and "lock_b" in cycles[0].message


def test_gl04_telemetry_fixture_detects_schema_drift():
    findings = lint_files("viol_gl04.py")
    assert rules_of(findings) == ["GL041", "GL042", "GL043", "GL044"]
    assert at_line(findings, "GL041") == [
        fixture_line("viol_gl04.py", "totally_unknown_event")]
    assert at_line(findings, "GL042") == [
        fixture_line("viol_gl04.py", 'emit_event("checkpoint_save", path="/x",')]
    assert at_line(findings, "GL043") == [
        fixture_line("viol_gl04.py", "# line 18: GL043")]
    assert at_line(findings, "GL044") == [
        fixture_line("viol_gl04.py", 'TICK_PHASES = (')]


def test_clean_fixture_reports_zero_findings():
    assert lint_files("clean.py") == []


def test_rule_catalog_covers_every_emitted_rule():
    findings = lint_files("viol_gl01.py", "viol_gl02.py", "viol_gl03.py",
                          "viol_gl04.py")
    for f in findings:
        assert f.rule in RULES, f


# ---------------------------------------------------------------------------
# suppressions + fingerprints + baseline
# ---------------------------------------------------------------------------

def test_inline_suppression_family_and_exact(tmp_path):
    src = (
        "import numpy as np\n"
        "# graft: hot-path\n"
        "def hot(stream):\n"
        "    a = float(stream)              # graft-ok: GL011 reason text\n"
        "    b = np.asarray(stream)         # graft-ok: GL01x family\n"
        "    # graft-ok: GL011 on the line above the finding\n"
        "    c = int(stream)\n"
        "    d = bool(stream)               # graft-ok: GL032 wrong rule\n"
        "    return a, b, c, d\n")
    path = tmp_path / "s.py"
    path.write_text(src)
    findings = run_checkers(parse_modules(str(tmp_path), [str(path)]))
    # only the wrong-rule suppression leaks through
    assert [(f.rule, f.line) for f in findings] == [("GL011", 8)]


def test_fingerprint_survives_line_drift():
    f1 = Finding("GL011", "a/b.py", 10, "m", "C.m", "x = float(y)")
    f2 = Finding("GL011", "a/b.py", 99, "m", "C.m", "x = float(y)")
    f3 = Finding("GL011", "a/b.py", 10, "m", "C.m", "x = float(z)")
    assert f1.fingerprint == f2.fingerprint      # line move: same debt
    assert f1.fingerprint != f3.fingerprint      # content change: new


def test_baseline_round_trip(tmp_path, capsys):
    """Findings baselined with --update-baseline gate clean on re-run;
    a NEW violation still fails."""
    from building_llm_from_scratch_tpu.analysis.runner import (
        load_baseline,
        save_baseline,
        split_baselined,
    )

    base = tmp_path / "baseline.json"
    fixture = os.path.join(FIXTURES, "viol_gl01.py")
    work = tmp_path / "work.py"
    work.write_text(open(fixture).read())

    findings = run_checkers(parse_modules(str(tmp_path), [str(work)]))
    n = save_baseline(str(base), findings, {})
    assert n == len(findings) == 4
    entries = json.load(open(base))["entries"]
    assert {e["rule"] for e in entries} == {"GL011", "GL012", "GL013"}
    assert all("UNREVIEWED" in e["reason"] for e in entries)

    findings = run_checkers(parse_modules(str(tmp_path), [str(work)]))
    new, old, stale = split_baselined(findings, load_baseline(str(base)))
    assert not new and not stale and len(old) == len(entries)

    # a fresh violation is NOT covered
    work.write_text(open(fixture).read().replace(
        "    return total",
        "    extra = float(total_new_sync)\n    return total"))
    findings = run_checkers(parse_modules(str(tmp_path), [str(work)]))
    new, _old, _stale = split_baselined(findings, load_baseline(str(base)))
    assert [f.rule for f in new] == ["GL011"]


def test_repo_gates_clean_against_checked_in_baseline(capsys):
    """THE acceptance property: the repo itself has zero findings above
    analysis/baseline.json, and every baselined entry carries a real
    reason (no silent suppressions)."""
    rc = lint_main([])
    out = capsys.readouterr().out
    assert rc == 0, out
    entries = json.load(open(default_baseline_path()))["entries"]
    for e in entries:
        assert e["reason"] and "UNREVIEWED" not in e["reason"], e


def test_runner_json_output_and_per_rule_counts(tmp_path, capsys):
    out_json = tmp_path / "f.json"
    rc = lint_main([os.path.join(FIXTURES, "viol_gl04.py"),
                    "--json", str(out_json)])
    assert rc == 1
    payload = json.load(open(out_json))
    assert payload["n_findings"] == payload["n_new"] == 4
    assert set(payload["per_rule"]) == {"GL041", "GL042", "GL043", "GL044"}
    text = capsys.readouterr().out
    # per-rule counts in the gate log (diffable)
    assert "GL041=1" in text and "GL044=1" in text


def test_module_entrypoint_runs():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "building_llm_from_scratch_tpu.analysis",
         "--rules"],
        capture_output=True, text=True, env=env, cwd=repo_root())
    assert proc.returncode == 0
    assert "GL011" in proc.stdout and "GL044" in proc.stdout


def test_discover_skips_fixtures():
    files = discover(repo_root())
    assert files, "discovery found nothing"
    assert not any("fixtures" in f for f in files)


def test_update_baseline_refuses_partial_scan(capsys):
    """--update-baseline with explicit paths must not clobber the
    checked-in repo baseline from a partial scan."""
    rc = lint_main([os.path.join(FIXTURES, "viol_gl01.py"),
                    "--update-baseline"])
    assert rc == 2
    assert "refusing" in capsys.readouterr().err


def test_with_body_timed_acquire_does_not_corrupt_held_set(tmp_path):
    """A `.acquire()` of a second lock inside a with-block must not eat
    the with-lock at block exit: accesses AFTER the with are unguarded."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._l1 = threading.Lock()\n"
        "        self._l2 = threading.Lock()\n"
        "        self.x = 0              # guarded-by: _l1\n"
        "    def m(self):\n"
        "        with self._l1:\n"
        "            got = self._l2.acquire(timeout=1)\n"
        "            self.x += 1\n"
        "        self.x += 1\n")
    path = tmp_path / "w.py"
    path.write_text(src)
    findings = run_checkers(parse_modules(str(tmp_path), [str(path)]))
    hits = [f for f in findings if f.rule == "GL031"]
    assert [f.line for f in hits] == [11], findings


def test_same_class_call_mediated_lock_cycle_detected(tmp_path):
    """An intra-class l1->l2 / l2->l1 cycle where each edge crosses a
    method call (never lexically nested) still triggers GL032."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._l1 = threading.Lock()\n"
        "        self._l2 = threading.Lock()\n"
        "    def a(self):\n"
        "        with self._l1:\n"
        "            self.take2()\n"
        "    def take2(self):\n"
        "        with self._l2:\n"
        "            pass\n"
        "    def c(self):\n"
        "        with self._l2:\n"
        "            self.take1()\n"
        "    def take1(self):\n"
        "        with self._l1:\n"
        "            pass\n")
    path = tmp_path / "c.py"
    path.write_text(src)
    findings = run_checkers(parse_modules(str(tmp_path), [str(path)]))
    cycles = [f for f in findings if f.rule == "GL032"]
    assert len(cycles) == 1, findings
    assert "_l1" in cycles[0].message and "_l2" in cycles[0].message


def test_jitted_lambda_body_is_purity_checked(tmp_path):
    src = (
        "import jax\n"
        "import random\n"
        "fwd = jax.jit(lambda p: random.random() * p)\n")
    path = tmp_path / "l.py"
    path.write_text(src)
    findings = run_checkers(parse_modules(str(tmp_path), [str(path)]))
    assert [f.rule for f in findings] == ["GL023"]
    assert findings[0].qualname == "<jitted lambda>"


def test_schema_loads_without_jax():
    """The lint gate's schema access must stay stdlib-only: loading the
    registry by file path may not drag in jax/numpy via obs/__init__."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; "
         "from building_llm_from_scratch_tpu.analysis.base import "
         "load_schema_module; m = load_schema_module(); "
         "assert 'jax' not in sys.modules, 'jax imported'; "
         "assert 'numpy' not in sys.modules, 'numpy imported'; "
         "print(len(m.EVENTS))"],
        capture_output=True, text=True, cwd=repo_root())
    assert proc.returncode == 0, proc.stderr
    assert int(proc.stdout.strip()) >= 20


# ---------------------------------------------------------------------------
# schema registry self-consistency
# ---------------------------------------------------------------------------

def test_schema_groups_are_registry_subsets():
    for group in (schema.INCIDENT_EVENTS, schema.REQUEST_EVENTS,
                  schema.SERVING_LIFECYCLE_EVENTS):
        for name in group:
            assert name in schema.EVENTS, name


def test_schema_validate_event():
    assert schema.validate_event("nope", {}) == [
        "unregistered event kind 'nope'"]
    assert schema.validate_event(
        "checkpoint_save", {"path": "/x", "seconds": 1.0}) == []
    missing = schema.validate_event("checkpoint_save", {"seconds": 1.0})
    assert missing and "path" in missing[0]
    unknown = schema.validate_event("checkpoint_save",
                                    {"path": "/x", "wat": 1})
    assert unknown and "wat" in unknown[0]
    # open_fields admits dynamic payloads but still enforces required
    assert schema.validate_event("watchdog_halt",
                                 {"reason": "spike", "anything": 1}) == []
    assert schema.validate_event("watchdog_halt", {"anything": 1})


def test_trace_reexports_schema_tables():
    from building_llm_from_scratch_tpu.obs import trace

    assert trace.TICK_PHASES is schema.TICK_PHASES
    assert trace.TRAIN_SEGMENTS is schema.TRAIN_SEGMENTS


# ---------------------------------------------------------------------------
# lock-order sanitizer (runtime twin of GL032)
# ---------------------------------------------------------------------------

def test_lock_sanitizer_catches_ab_ba_inversion():
    san = LockOrderSanitizer()
    a = san.wrap(threading.Lock(), "A")
    b = san.wrap(threading.Lock(), "B")
    with a:
        with b:
            pass
    assert san.inversions() == []
    with b:
        with a:                    # inverse order: flagged
            pass
    inv = san.inversions()
    assert len(inv) == 1
    assert {inv[0].lock, inv[0].other} == {"A", "B"}
    assert "A -> B" in inv[0].detail or "B -> A" in inv[0].detail


def test_lock_sanitizer_inversion_across_threads():
    san = LockOrderSanitizer()
    a = san.wrap(threading.Lock(), "A")
    b = san.wrap(threading.Lock(), "B")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with b:
        with a:
            pass
    assert len(san.inversions()) == 1
    assert san.inversions()[0].thread == threading.current_thread().name


def test_lock_sanitizer_reentrant_and_hold_time():
    san = LockOrderSanitizer(hold_threshold_s=0.02)
    r = san.wrap(threading.RLock(), "R")
    with r:
        with r:                    # reentry: no self-edge, no violation
            pass
        time.sleep(0.05)
    kinds = [v.kind for v in san.violations]
    assert kinds == ["hold_time"]
    assert "R" in san.report()


def test_lock_sanitizer_raise_mode():
    san = LockOrderSanitizer(raise_on_violation=True)
    a = san.wrap(threading.Lock(), "A")
    b = san.wrap(threading.Lock(), "B")
    with a:
        with b:
            pass
    with pytest.raises(RuntimeError, match="inversion"):
        with b:
            with a:
                pass
    # the aborted acquisition neither leaked the inner lock nor left
    # stale held state: the same inverted order raises again cleanly
    assert a._inner.acquire(blocking=False)
    a._inner.release()
    assert san._stack() == []


def test_lock_sanitizer_instruments_a_live_engine():
    """Integration: a real DecodeEngine serving real requests through
    sanitized locks shows NO inversions and no over-threshold holds —
    the dynamic proof behind the GL032 static pass."""
    jax = pytest.importorskip("jax")
    import numpy as np

    from building_llm_from_scratch_tpu.configs import ModelConfig
    from building_llm_from_scratch_tpu.models import init_params
    from building_llm_from_scratch_tpu.serving import (
        DecodeEngine,
        SamplingParams,
    )

    cfg = ModelConfig(name="lint-tiny", vocab_size=96, context_length=64,
                      emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                      n_kv_groups=2, norm="layernorm", positional="learned",
                      activation="gelu", drop_rate=0.0, eos_id=1)
    eng = DecodeEngine(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                       n_slots=2, max_len=64, metrics_every=0,
                       watch_compiles=False)
    eng.warmup()
    san = LockOrderSanitizer(hold_threshold_s=30.0)
    wrapped = san.instrument(eng, ("_lock", "_restart_lock"),
                             prefix="engine")
    assert wrapped == ["engine._lock", "engine._restart_lock"]
    handles = [eng.submit(np.array([3, 4], np.int32),
                          SamplingParams(max_new_tokens=4, ignore_eos=True,
                                         seed=i))
               for i in range(3)]
    eng.run_until_idle()
    for h in handles:
        h.result(timeout=10)
    assert san.violations == [], san.report()


# ---------------------------------------------------------------------------
# transfer sentry (runtime twin of GL01x) — unit level; the engine/
# trainer integration smokes live in tests/test_trace.py
# ---------------------------------------------------------------------------

def test_transfer_sentry_blocks_implicit_allows_explicit():
    jax = pytest.importorskip("jax")
    import numpy as np

    x = jax.numpy.arange(4.0)
    with no_implicit_device_to_host():
        host = jax.device_get(x)              # explicit: fine
        assert float(host[0]) == 0.0          # host numpy: fine
        with pytest.raises(ImplicitTransferError):
            float(x[0])
        with pytest.raises(ImplicitTransferError):
            np.asarray(x)
        with pytest.raises(ImplicitTransferError):
            bool(x[0] > 0)
        with pytest.raises(ImplicitTransferError):
            x[0].item()
    # patches are restored on exit
    assert float(x[1]) == 1.0
    assert np.asarray(x).shape == (4,)
