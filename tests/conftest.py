"""Test configuration: force an 8-device virtual CPU platform.

This is the TPU-world analog of a fake distributed backend (SURVEY.md §4):
all sharding/collective tests run on 8 virtual CPU devices via
``--xla_force_host_platform_device_count``.

Note: this environment's sitecustomize registers a TPU PJRT plugin and pins
``JAX_PLATFORMS=axon`` at interpreter startup, so plain env vars are not
enough — we must flip ``jax_platforms`` via jax.config after import (backends
initialize lazily, so the XLA_FLAGS below still take effect).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng_key():
    return jax.random.PRNGKey(0)


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_backend():
    assert jax.default_backend() == "cpu"
    assert len(jax.devices()) == 8
    yield
