"""Test configuration: force an 8-device virtual CPU platform.

This is the TPU-world analog of a fake distributed backend (SURVEY.md §4):
all sharding/collective tests run on 8 virtual CPU devices via
``--xla_force_host_platform_device_count``.

Note: this environment's sitecustomize registers a TPU PJRT plugin and pins
``JAX_PLATFORMS=axon`` at interpreter startup, so plain env vars are not
enough — we must flip ``jax_platforms`` via jax.config after import (backends
initialize lazily, so the XLA_FLAGS below still take effect).

Set ``RUN_TPU_TESTS=1`` to SKIP the CPU forcing and run on the real chip
instead — this enables the ``@needs_tpu`` pallas-kernel tests
(test_fused_attention.py, the pallas cases in test_attention_impls.py) that
skip on the virtual CPU mesh:

  RUN_TPU_TESTS=1 python -m pytest tests/test_fused_attention.py -q
"""

import os
import tempfile

RUN_ON_TPU = os.environ.get("RUN_TPU_TESTS") == "1"

if not RUN_ON_TPU:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not RUN_ON_TPU:
    jax.config.update("jax_platforms", "cpu")

# NOTE on the XLA persistent compilation cache: do NOT enable it for this
# suite. On this jaxlib CPU build, executables deserialized from the cache
# lose their donation/alias metadata (memory_analysis alias bytes come back
# 0, corrupting the perf fingerprints) and a subsequent donated-buffer
# execution aborts the process (SIGABRT reproduced via test_cli_resume).
# run_bench additionally pins cold-compile semantics for fingerprints even
# when a cache is ambiently configured.

import contextlib  # noqa: E402

import pytest  # noqa: E402


@contextlib.contextmanager
def distributed_spawn_lock():
    """Cross-xdist-worker file lock for tests that spawn their own
    jax.distributed process groups: two groups forming concurrently can
    race on coordinator ports (observed as Gloo 'connected to N peer
    ranks' failures when the 2-proc and 4-proc tests overlapped under
    ``-n 4``). Serializing group formation removes the race; the lock is
    a no-op when the suite runs single-process."""
    import fcntl

    path = os.path.join(tempfile.gettempdir(), "bllm_dist_spawn.lock")
    with open(path, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


@pytest.fixture(autouse=True)
def _scrub_stale_ckpt_staging():
    """Remove checkpoint staging dirs (model_pg_*.tmp/.old) a crashed or
    interrupted test left in the working tree, so one test's aborted save
    can never feed a later test's auto-resume discovery."""
    yield
    import glob
    import shutil

    for root in (os.getcwd(), os.path.join(os.getcwd(),
                                           "model_checkpoints")):
        for suffix in (".tmp", ".old"):
            for d in glob.glob(os.path.join(root, f"model_pg_*{suffix}")):
                if os.path.isdir(d):
                    shutil.rmtree(d, ignore_errors=True)


@pytest.fixture(scope="session")
def rng_key():
    return jax.random.PRNGKey(0)


@pytest.fixture(scope="session", autouse=True)
def _assert_backend():
    if RUN_ON_TPU:
        assert jax.default_backend() == "tpu"
    else:
        assert jax.default_backend() == "cpu"
        assert len(jax.devices()) == 8
    yield
