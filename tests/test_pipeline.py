"""Pipeline parallelism parity on the 8-device CPU mesh: GPipe scheduling
is placement, not semantics — loss and gradients must match single-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import get_config
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.parallel.pipeline import (
    make_pp_loss_fn,
    make_pp_mesh,
    make_pp_train_step,
    stage_shardings,
)
from building_llm_from_scratch_tpu.training import (
    build_optimizer,
    init_train_state,
    make_train_step,
)
from building_llm_from_scratch_tpu.training.train_step import (
    cross_entropy_loss,
)



# jax<0.5 (no jax.shard_map alias) cannot transpose a shard_map whose out
# is a replicated scalar (the pipeline loss): jax.experimental.shard_map
# raises _SpecError in the grad path (fixed upstream alongside the alias).
# Forward/eval pp paths work; only grad-through tests are affected.
needs_shard_map_transpose = pytest.mark.xfail(
    not hasattr(jax, "shard_map"),
    reason="shard_map transpose of a replicated scalar out is broken on "
           "this jax version (fixed upstream with jax.shard_map)",
    strict=False)

def _cfg(n_layers=4):
    return get_config("llama3_2", "1B", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=512, context_length=64,
        n_layers=n_layers, drop_rate=0.0, dtype="fp32")


def _batch(cfg, bs=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, cfg.vocab_size, (bs, cfg.context_length)).astype(
        np.int32)
    return {"inputs": x, "targets": np.roll(x, -1, 1).astype(np.int32),
            "weights": np.ones_like(x, np.float32)}


def _ref_loss(params, cfg, batch):
    from building_llm_from_scratch_tpu.models import forward

    logits = forward(params, cfg, jnp.asarray(batch["inputs"]))
    return cross_entropy_loss(logits, jnp.asarray(batch["targets"]),
                              jnp.asarray(batch["weights"]))


@pytest.mark.parametrize("stages,n_micro", [(2, 2), (4, 4), (8, 8)])
def test_pp_loss_matches_single_device(stages, n_micro):
    # stages < 8 leave devices for the data axis: (data=4,stage=2) etc.
    cfg = _cfg(n_layers=8)
    mesh = make_pp_mesh(stages)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    want = float(_ref_loss(params, cfg, batch))
    loss_fn = make_pp_loss_fn(cfg, mesh, n_micro)
    got = float(jax.jit(loss_fn)(params, batch))
    assert abs(got - want) < 1e-5, (got, want)


@needs_shard_map_transpose
def test_pp_gradients_match_single_device():
    cfg = _cfg(n_layers=4)
    mesh = make_pp_mesh(4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    gw = jax.grad(lambda p: _ref_loss(p, cfg, batch))(params)
    loss_fn = make_pp_loss_fn(cfg, mesh, n_micro=4)
    gp = jax.jit(jax.grad(loss_fn))(params, batch)
    flat_w = jax.tree_util.tree_leaves_with_path(gw)
    flat_p = jax.tree_util.tree_leaves(gp)
    for (path, a), b in zip(flat_w, flat_p):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5, rtol=1e-4,
            err_msg=str(path))


@needs_shard_map_transpose
def test_pp_training_matches_single_device():
    """3 pipelined train steps == 3 single-device steps."""
    cfg = _cfg(n_layers=8)
    mesh = make_pp_mesh(4)
    opt = build_optimizer(peak_lr=1e-3, warmup_steps=2, total_steps=10)
    batches = [_batch(cfg, seed=s) for s in range(3)]

    ref_state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                                 opt, jax.random.PRNGKey(0))
    ref_step = make_train_step(cfg, opt)
    ref_losses = []
    for b in batches:
        ref_state, m = ref_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                             opt, jax.random.PRNGKey(0))
    state = jax.device_put(state, stage_shardings(state, mesh))
    step = make_pp_train_step(cfg, opt, mesh, n_micro=4)
    losses = []
    for b in batches:
        state, m = step(state, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)
    ref_w = np.asarray(ref_state["trainable"]["blocks"]["attn"]["wq"])
    got_w = np.asarray(jax.device_get(
        state["trainable"]["blocks"]["attn"]["wq"]))
    np.testing.assert_allclose(got_w, ref_w, rtol=2e-3, atol=2e-5)


@needs_shard_map_transpose
def test_pp_tp_loss_and_gradients_match_single_device():
    """pp x tp (round-5 VERDICT #6): (data=2, stage=2, model=2) mesh —
    loss AND every RAW gradient leaf match single-device. No manual
    gradient corrections exist or are needed: jax's shard_map transpose
    differentiates through the Megatron psums exactly (the torch-world
    f/g conjugate pair is an autograd workaround jax does not require —
    an earlier draft that added it produced garbage gradients)."""
    from building_llm_from_scratch_tpu.parallel.pipeline import MODEL_AXIS

    cfg = _cfg(n_layers=4)
    mesh = make_pp_mesh(2, tp=2)
    assert mesh.shape == {"data": 2, "stage": 2, MODEL_AXIS: 2}
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)

    want = float(_ref_loss(params, cfg, batch))
    loss_fn = make_pp_loss_fn(cfg, mesh, n_micro=2)
    got = float(jax.jit(loss_fn)(params, batch))
    assert abs(got - want) < 1e-5, (got, want)

    # RAW gradient parity — adam-step parity alone would be blind to
    # per-leaf scale errors (m/sqrt(v) cancels constant factors)
    gw = jax.grad(lambda p: _ref_loss(p, cfg, batch))(params)
    gp = jax.jit(jax.grad(loss_fn))(params, batch)
    for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(gw),
                            jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5, rtol=1e-4,
            err_msg=str(path))

    # gradient parity through the full train step (which applies the
    # replicated-grad 1/tp correction)
    opt = build_optimizer(peak_lr=1e-3, warmup_steps=2, total_steps=10)
    ref_state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                                 opt, jax.random.PRNGKey(0))
    ref_step = make_train_step(cfg, opt)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                             opt, jax.random.PRNGKey(0))
    state = jax.device_put(state, stage_shardings(state, mesh))
    step = make_pp_train_step(cfg, opt, mesh, n_micro=2)
    for seed in range(2):
        b = _batch(cfg, seed=seed)
        ref_state, mr = ref_step(ref_state, b)
        state, mp = step(state, b)
        np.testing.assert_allclose(float(mp["loss"]), float(mr["loss"]),
                                   rtol=2e-4, atol=2e-5)
    for (path, a), b in zip(
            jax.tree_util.tree_leaves_with_path(ref_state["trainable"]),
            jax.tree_util.tree_leaves(state["trainable"])):
        np.testing.assert_allclose(np.asarray(jax.device_get(b)),
                                   np.asarray(a), rtol=2e-3, atol=2e-5,
                                   err_msg=str(path))


def test_pp_tp_state_shardings_split_model_axis():
    from jax.sharding import PartitionSpec as P

    cfg = _cfg(n_layers=4)
    mesh = make_pp_mesh(2, tp=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    sh = stage_shardings(params, mesh)
    assert sh["blocks"]["attn"]["wq"].spec == P("stage", None, "model")
    assert sh["blocks"]["attn"]["wo"].spec == P("stage", "model")
    assert sh["blocks"]["mlp"]["up"].spec == P("stage", None, "model")
    assert sh["blocks"]["mlp"]["down"].spec == P("stage", "model")
    assert sh["blocks"]["norm1"]["scale"].spec == P("stage")
    assert sh["tok_emb"]["weight"].spec == P()


@needs_shard_map_transpose
def test_pp_tp_dropout_trains_gpt2():
    """GPT-2 (dropout 0.1, qkv biases) under pp x tp: runs and the loss is
    finite — attention masks fold the model-shard index, residual masks
    stay shard-identical (transformer._block)."""
    cfg = get_config("GPT2", "124M", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=512, context_length=64,
        n_layers=4, dtype="fp32")
    mesh = make_pp_mesh(2, tp=2)
    opt = build_optimizer(total_steps=10)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)),
                             opt, jax.random.PRNGKey(0))
    state = jax.device_put(state, stage_shardings(state, mesh))
    step = make_pp_train_step(cfg, opt, mesh, n_micro=2)
    losses = []
    for seed in range(3):
        state, m = step(state, _batch(cfg, seed=seed))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all(), losses


@needs_shard_map_transpose
def test_pp_lora_matches_single_device():
    """pp + LoRA: adapters merge before the stage split; losses match the
    plain LoRA step and ONLY the adapters update."""
    from building_llm_from_scratch_tpu.models.lora import init_lora_params

    cfg = _cfg(n_layers=4)
    mesh = make_pp_mesh(4)
    opt = build_optimizer(peak_lr=1e-2, total_steps=10)
    params = init_params(cfg, jax.random.PRNGKey(0))
    # host snapshot: both states get their OWN device copies (the donated
    # steps delete their input buffers — the aliasing footgun of VERDICT r2)
    base_np = jax.tree_util.tree_map(np.asarray, params)
    fresh_base = lambda: jax.tree_util.tree_map(jnp.asarray, base_np)
    batches = [_batch(cfg, seed=s) for s in range(3)]

    lora = init_lora_params(cfg, params, jax.random.PRNGKey(1), rank=4)
    ref_state = init_train_state(lora, opt, jax.random.PRNGKey(0),
                                 frozen=fresh_base())
    ref_step = make_train_step(cfg, opt, lora_alpha=8, lora_rank=4)
    ref_losses = []
    for b in batches:
        ref_state, m = ref_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    lora2 = init_lora_params(cfg, params, jax.random.PRNGKey(1), rank=4)
    state = init_train_state(lora2, opt, jax.random.PRNGKey(0),
                             frozen=fresh_base())
    state = jax.device_put(state, stage_shardings(state, mesh))
    step = make_pp_train_step(cfg, opt, mesh, n_micro=4, lora_alpha=8,
                              lora_rank=4)
    losses = []
    for b in batches:
        state, m = step(state, b)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-5)
    # base stays frozen; adapters moved
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
        state["frozen"], base_np)
    assert float(jnp.abs(
        state["trainable"]["blocks"]["attn"]["wq"]["B"]).max()) > 0


def test_pp_param_spec_for_weight_loading():
    """The weight-conversion path places each tensor via plan.param_spec:
    block leaves stage-shard their layer axis, non-divisible or non-block
    leaves replicate."""
    from jax.sharding import PartitionSpec as P

    from building_llm_from_scratch_tpu.parallel.pipeline import PipelinePlan

    plan = PipelinePlan(make_pp_mesh(2), n_micro=2)
    assert plan.param_spec(("blocks", "attn", "wq"), (4, 64, 64)) \
        == P("stage")
    assert plan.param_spec(("blocks", "norm1", "scale"), (3, 64)) == P()
    assert plan.param_spec(("tok_emb", "weight"), (512, 64)) == P()

    # end-to-end: a converted leaf placed with this spec spans the mesh
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    leaf = jnp.zeros((4, 8, 8))
    placed = jax.device_put(leaf, NamedSharding(
        plan.mesh, plan.param_spec(("blocks", "attn", "wq"), leaf.shape)))
    assert len(placed.sharding.device_set) == 8      # (data=4, stage=2)


def test_pp_rejects_bad_shapes():
    cfg = _cfg(n_layers=6)
    mesh = make_pp_mesh(4)
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_loss_fn(cfg, mesh, n_micro=2)


# ---------------------------------------------------------------------------
# round-4 (pipeline v2): remat opt-in, dropout, drain-tick gating
# ---------------------------------------------------------------------------

@needs_shard_map_transpose
def test_pp_gradients_match_with_and_without_remat():
    """--use_actv_ckpt only changes memory/recompute, never values: pp
    grads with remat on == off (and == single-device)."""
    cfg = _cfg(n_layers=4)
    mesh = make_pp_mesh(2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg, bs=16)   # (data=4, stage=2): Bm must divide 4

    def grads_for(c):
        loss_fn = make_pp_loss_fn(c, mesh, n_micro=4)
        return jax.jit(jax.grad(lambda p: loss_fn(p, batch)))(params)

    g_plain = grads_for(cfg)
    g_remat = grads_for(cfg.replace(use_actv_ckpt=True))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g_plain, g_remat)


@needs_shard_map_transpose
def test_pp_dropout_trains_gpt2():
    """GPT-2 (dropout 0.1) pipelines since v2: per-(micro,data,stage,layer)
    folded masks; losses finite and decreasing on a repeated batch."""
    cfg = get_config("GPT2", "124M", debug=True).replace(
        emb_dim=64, hidden_dim=128, vocab_size=256, context_length=64,
        n_heads=4, n_layers=4, dtype="fp32")
    assert cfg.drop_rate > 0.0
    mesh = make_pp_mesh(2)
    opt = build_optimizer(total_steps=12)
    state = init_train_state(init_params(cfg, jax.random.PRNGKey(0)), opt,
                             jax.random.PRNGKey(1))
    step = make_pp_train_step(cfg, opt, mesh, n_micro=4)
    batch = _batch(cfg, bs=16)
    losses = []
    for _ in range(10):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_pp_dropout_deterministic_per_step_rng():
    """Same state (rng, step) -> identical pp loss; different step ->
    different masks."""
    cfg = _cfg(n_layers=4).replace(drop_rate=0.3)
    mesh = make_pp_mesh(2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg, bs=16)
    loss_fn = jax.jit(make_pp_loss_fn(cfg, mesh, n_micro=4))
    rng = jax.random.PRNGKey(5)
    a = float(loss_fn(params, batch, rng))
    b = float(loss_fn(params, batch, rng))
    assert a == b
    c = float(loss_fn(params, batch, jax.random.PRNGKey(6)))
    assert a != c
    # rng=None -> deterministic path, matches the no-dropout reference
    want = float(_ref_loss(params, cfg.replace(drop_rate=0.0), batch))
    got = float(loss_fn(params, batch))
    assert abs(got - want) < 1e-5
