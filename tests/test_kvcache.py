"""KV-cache memory engine tests (serving/kvcache.py + the model/ops/
engine integration): KVCachePolicy allocation (the one rule behind
train-time ``init_cache`` and serving ``init_slot_cache``), int8 slot KV
(bytes halved, tolerance-pinned parity), prefix store LRU/pinning units,
engine-vs-generate() token parity with the prefix cache ON, chunked
co-resident isolation, zero-FLOP cached spans (forward-call spy), and
zero recompiles across hit/miss/evict under live traffic.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.generate import generate
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.models.transformer import (
    decode_slots,
    init_cache,
    init_slot_cache,
    prefill_into_slot,
)
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    KVCachePolicy,
    PrefixStore,
    SamplingParams,
)
from building_llm_from_scratch_tpu.serving.kvcache import (
    cache_nbytes,
    extract_prefix_panes,
)

INT8 = KVCachePolicy(kv_quant="int8")


def tiny_cfg(ctx=256, **kw):
    base = dict(name="kv-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def solo_tokens(params, cfg, prompt, sp: SamplingParams):
    out, n = generate(params, cfg, np.asarray(prompt)[None],
                      max_new_tokens=sp.max_new_tokens,
                      temperature=sp.temperature, top_k=sp.top_k,
                      eos_id=(None if sp.ignore_eos
                              else (sp.eos_id if sp.eos_id is not None
                                    else cfg.eos_id)),
                      rng=jax.random.PRNGKey(sp.seed),
                      return_n_generated=True)
    Tp = len(prompt)
    return [int(t) for t in out[0, Tp: Tp + int(n[0])]]


def shared_prefix_prompts(cfg, n, prefix_len=40, seed=0):
    rng = np.random.default_rng(seed)
    prefix = rng.integers(2, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    return [np.concatenate([prefix, rng.integers(
        2, cfg.vocab_size, (2 + i % 3,)).astype(np.int32)])
        for i in range(n)]


# ---------------------------------------------------------------------------
# KVCachePolicy: the one allocation rule
# ---------------------------------------------------------------------------

def test_policy_alloc_backs_both_cache_inits(model):
    """Train-time ``init_cache`` and serving ``init_slot_cache`` must
    allocate through the SAME policy rule: identical per-layer layout
    and dtype (the three formerly-duplicated jnp.zeros blocks)."""
    cfg, _ = model
    train = init_cache(cfg, batch_size=3, max_length=32)
    serve = init_slot_cache(cfg, n_slots=3, max_length=32)
    for name in ("k", "v"):
        assert len(train[name]) == cfg.n_layers
        for a, b in zip(train[name], serve[name]):
            assert a.shape == b.shape == (3, cfg.n_kv_groups, 32,
                                          cfg.head_dim)
            assert a.dtype == b.dtype == cfg.jax_dtype
    assert train["length"].dtype == jnp.int32
    assert "k_scale" not in serve          # default policy: no sidecars


def test_policy_int8_alloc_and_bytes(model):
    """int8 policy: int8 k/v + fp32 per-position scale sidecars; the KV
    DATA bytes halve exactly vs bf16 (int8 vs 2-byte elements) and total
    cache bytes (incl. the scale sidecar) stay under 0.6x."""
    cfg, _ = model
    cache = init_slot_cache(cfg, 2, 32, policy=INT8)
    assert cache["k"][0].dtype == jnp.int8
    assert cache["k_scale"][0].shape == (2, cfg.n_kv_groups, 32, 1)
    assert cache["k_scale"][0].dtype == jnp.float32

    bf16 = KVCachePolicy()
    cfg16 = tiny_cfg(dtype="bf16")
    b_bf16 = bf16.bytes_per_slot(cfg16, 128)
    b_int8 = INT8.bytes_per_slot(cfg16, 128)
    assert b_int8["kv_bytes"] * 2 == b_bf16["kv_bytes"]
    # total incl. the fp32 scale sidecar: (hd + 4) / (2 * hd) of bf16 —
    # 0.625x on this tiny model's hd=16, 0.53x at a real hd=64
    hd = cfg16.head_dim
    assert b_int8["total_bytes"] * 2 * hd == b_bf16["total_bytes"] * (hd + 4)
    from building_llm_from_scratch_tpu.configs import get_config

    real = get_config("GPT2", "124M", dtype="bf16")
    r8 = INT8.bytes_per_slot(real, real.context_length)
    r16 = bf16.bytes_per_slot(real, real.context_length)
    assert r8["kv_bytes"] * 2 == r16["kv_bytes"]
    assert r8["total_bytes"] <= 0.54 * r16["total_bytes"]
    # the reported bytes match the real allocation, measured via nbytes
    cache8 = init_slot_cache(cfg16, 2, 128, policy=INT8)
    assert cache_nbytes(cache8) == 2 * b_int8["total_bytes"]


def test_policy_validation():
    with pytest.raises(ValueError, match="kv_quant"):
        KVCachePolicy(kv_quant="fp8")
    with pytest.raises(ValueError, match="prefill_chunk"):
        KVCachePolicy(prefill_chunk=-1)
    with pytest.raises(ValueError, match="chunked prefill"):
        KVCachePolicy(prefix_cache=True, prefill_chunk=0)


# ---------------------------------------------------------------------------
# int8 quantization: ops-level + decode parity tolerance
# ---------------------------------------------------------------------------

def test_quantize_kv_roundtrip_bound():
    """Symmetric int8 roundtrip error is bounded by scale/2 = amax/254
    per element; exact-zero rows stay exactly zero (pane determinism)."""
    from building_llm_from_scratch_tpu.ops.decode_step import (
        dequantize_kv,
        quantize_kv,
    )

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 8, 16))
    codes, scale = quantize_kv(x)
    assert codes.dtype == jnp.int8 and scale.shape == (2, 3, 8, 1)
    err = np.abs(np.asarray(dequantize_kv(codes, scale)) - np.asarray(x))
    amax = np.max(np.abs(np.asarray(x)), axis=-1, keepdims=True)
    assert (err <= amax / 254.0 + 1e-7).all()
    z_codes, z_scale = quantize_kv(jnp.zeros((1, 2, 4, 8)))
    assert (np.asarray(z_codes) == 0).all()
    assert (np.asarray(dequantize_kv(z_codes, z_scale)) == 0).all()


#: pinned int8-vs-fp32 decode logits tolerance (documented in README):
#: per-element quant error is ~0.4% of each head's amax; through two
#: layers of attention+MLP it stays within ~0.15 absolute on this tiny
#: model's fp32 logits. Measured max |delta| ~0.04; pinned 4x slack.
INT8_LOGITS_ATOL = 0.15


def test_decode_slots_int8_logits_within_pinned_tolerance(model):
    """decode_slots over an int8 cache vs the fp32 cache, same prompt
    state (written through the real prefill path so cache contents are
    the quantized/exact twins of each other): logits within the pinned
    tolerance, and the int8 cache really is int8 on device."""
    cfg, params = model
    prompt = np.arange(2, 22, dtype=np.int32)[None]
    Tp = prompt.shape[1]
    out = {}
    for name, policy in (("fp32", KVCachePolicy()), ("int8", INT8)):
        cache = init_slot_cache(cfg, 2, 64, policy=policy)
        _logits, cache = prefill_into_slot(
            params, cfg, jnp.asarray(prompt), jnp.asarray(Tp, jnp.int32),
            jnp.asarray(0, jnp.int32), cache)
        lengths = jnp.asarray([Tp, 0], jnp.int32)
        toks = jnp.asarray([[5], [0]], jnp.int32)
        logits, _ = decode_slots(params, cfg, toks, lengths, cache)
        out[name] = np.asarray(logits[0])
    assert np.isfinite(out["int8"]).all()
    delta = np.abs(out["int8"] - out["fp32"]).max()
    assert delta <= INT8_LOGITS_ATOL, delta
    assert delta > 0                      # actually exercised the quant


def test_int8_engine_end_to_end_and_memory(model):
    """int8 engine: requests complete with zero recompiles; greedy
    tokens agree with the fp32 solo run on a clear-margin model (pinned
    >= 75% agreement — bit-exactness is NOT promised under quant, the
    tolerance above is the contract); the live cache's device bytes are
    under 0.6x of the fp32 policy's."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=64, kv_policy=INT8)
    eng.warmup()
    prompt = np.arange(2, 14, dtype=np.int32)
    sp = SamplingParams(max_new_tokens=8, ignore_eos=True, seed=5)
    h = eng.submit(prompt, sp)
    eng.run_until_idle()
    assert h.finish_reason == "length" and len(h.output_ids) == 8
    assert eng.n_recompiles == 0
    ref = solo_tokens(params, cfg, prompt, sp)
    agree = sum(a == b for a, b in zip(h.output_ids, ref)) / len(ref)
    assert agree >= 0.75, (h.output_ids, ref)
    fp32_bytes = cache_nbytes(init_slot_cache(cfg, 2, 64))
    assert cache_nbytes(eng.cache) <= 0.6 * fp32_bytes


# ---------------------------------------------------------------------------
# prefix store units: LRU, budget, pinning, determinism
# ---------------------------------------------------------------------------

def _panes(nbytes_target=1024, fill=0.0):
    n = max(nbytes_target // 4, 1)
    return {"k": jnp.full((n,), fill, jnp.float32)}


def test_prefix_store_lru_eviction_under_budget():
    store = PrefixStore("fp", chunk_tokens=4, budget_bytes=3 * 1024,
                        pane_tokens=64)
    ids = [np.arange(i, i + 8, dtype=np.int32) for i in range(4)]
    for i in range(3):
        assert store.insert(ids[i], "base", _panes(1024))
    assert store.n_entries == 3
    # touch entry 0 (LRU refresh), insert a 4th: entry 1 must evict
    span, e0 = store.match(np.concatenate([ids[0], [99]]), "base")
    assert e0 is not None and span == 8
    store.release(e0)
    assert store.insert(ids[3], "base", _panes(1024))
    assert store.n_entries == 3
    assert store.n_evictions == 1
    assert store.contains(ids[0], "base")          # refreshed: survived
    assert not store.contains(ids[1], "base")      # LRU victim
    # an entry bigger than the whole budget is refused outright
    assert not store.insert(np.arange(50, 58, dtype=np.int32), "base",
                            _panes(64 * 1024))
    assert store.n_insert_skips == 1


def test_prefix_store_pinned_entries_never_evict():
    store = PrefixStore("fp", chunk_tokens=4, budget_bytes=2 * 1024,
                        pane_tokens=64)
    a = np.arange(0, 8, dtype=np.int32)
    b = np.arange(10, 18, dtype=np.int32)
    assert store.insert(a, "base", _panes(1024))
    assert store.insert(b, "base", _panes(1024))
    # pin A (an in-flight copy holds it); C's insert may only evict B
    _span, ea = store.match(np.concatenate([a, [99]]), "base")
    assert ea is not None
    assert store.insert(np.arange(20, 28, dtype=np.int32), "base",
                        _panes(1024))
    assert store.contains(a, "base")
    assert not store.contains(b, "base")
    # everything evictable pinned -> insert refuses rather than corrupts
    _sp, ec = store.match(np.arange(20, 29, dtype=np.int32), "base")
    assert ec is not None
    assert not store.insert(np.arange(30, 38, dtype=np.int32), "base",
                            _panes(2048))
    store.release(ea)
    store.release(ec)


def test_prefix_store_namespacing_and_span_semantics():
    store = PrefixStore("fp", chunk_tokens=4, budget_bytes=1 << 20,
                        pane_tokens=12)
    ids = np.arange(0, 8, dtype=np.int32)
    store.insert(ids, "tenant-a#1", _panes())
    # same tokens, other namespace (base / reloaded adapter): no hit
    assert store.match(np.concatenate([ids, [1]]), "base")[1] is None
    assert store.match(np.concatenate([ids, [1]]), "tenant-a#2")[1] is None
    span, e = store.match(np.concatenate([ids, [1]]), "tenant-a#1")
    assert span == 8
    store.release(e)
    # a hit must leave >= 1 suffix token: an 8-token prompt can match at
    # most span 4 of the stored 8 (storable_span caps at Tp-1)
    assert store.storable_span(8) == 4
    assert store.storable_span(9) == 8
    assert store.storable_span(17) == 12       # pane_tokens cap
    # min_span: the catch-up probe ignores spans it already holds
    assert store.match(np.concatenate([ids, [1]]), "tenant-a#1",
                       min_span=8, count_miss=False)[1] is None


def test_extract_prefix_panes_zero_clamps_shareable_state(model):
    """Two donors sharing a prefix but with different suffixes (and
    different pad garbage beyond their prompts) must extract BYTE-
    IDENTICAL panes for the shared span — the satellite fix: pad/suffix
    state is zero-clamped, so a cached prefix is deterministic and its
    audit/hash is stable."""
    cfg, params = model
    prefix = np.arange(2, 12, dtype=np.int32)
    panes = []
    for suffix in ([33, 34, 35], [44]):
        prompt = np.concatenate([prefix, np.asarray(suffix, np.int32)])
        cache = init_slot_cache(cfg, 1, 32)
        _l, cache = prefill_into_slot(
            params, cfg, jnp.asarray(prompt[None]),
            jnp.asarray(len(prompt), jnp.int32),
            jnp.asarray(0, jnp.int32), cache)
        panes.append(extract_prefix_panes(
            cache, jnp.asarray(0, jnp.int32),
            jnp.asarray(len(prefix), jnp.int32), pane_len=16))
    for name in panes[0]:
        a, b = np.asarray(panes[0][name]), np.asarray(panes[1][name])
        np.testing.assert_array_equal(a, b)
        assert (a[:, :, len(prefix):] == 0).all()   # clamped tail


def test_prefill_writes_zero_not_garbage_at_pads(model):
    """The direct form of the pad-garbage fix: bucketed prefill's pad
    positions land as exact zeros in the slot cache."""
    cfg, params = model
    prompt = np.arange(2, 7, dtype=np.int32)       # 5 real tokens
    padded = np.zeros((1, 16), np.int32)
    padded[0, :5] = prompt
    cache = init_slot_cache(cfg, 1, 32)
    # dirty the cache first so zeros must be WRITTEN, not inherited
    cache = {k: [jnp.full_like(b, 7.0) for b in v]
             for k, v in cache.items()}
    _l, cache = prefill_into_slot(
        params, cfg, jnp.asarray(padded), jnp.asarray(5, jnp.int32),
        jnp.asarray(0, jnp.int32), cache)
    for name in ("k", "v"):
        pane = np.asarray(cache[name][0])[0]       # (Hkv, Tmax, hd)
        assert (pane[:, 5:16] == 0).all()          # pad span zeroed
        assert np.abs(pane[:, :5]).sum() > 0       # real KV written


# ---------------------------------------------------------------------------
# engine integration: parity, isolation, zero-FLOP hits, zero recompiles
# ---------------------------------------------------------------------------

CHUNKED = KVCachePolicy(prefill_chunk=16)
PREFIXED = KVCachePolicy(prefill_chunk=16, prefix_cache=True,
                         prefix_budget_bytes=8 << 20)


def test_engine_parity_with_prefix_cache_on_greedy_and_sampled(model):
    """Engine-vs-generate() token parity with the prefix cache ON:
    greedy AND seeded sampling, where the second/third requests HIT the
    first's cached prefix — reused KV must be bit-identical to
    recomputed KV (model-dtype policy), so tokens match exactly."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=3, max_len=128,
                       warmup_prompt_cap=64, kv_policy=PREFIXED)
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 3)
    cases = [
        SamplingParams(max_new_tokens=8, ignore_eos=True, seed=3),
        SamplingParams(max_new_tokens=8, temperature=1.0, top_k=5,
                       ignore_eos=True, seed=3),
        SamplingParams(max_new_tokens=6, temperature=0.7, top_k=13,
                       ignore_eos=True, seed=11),
    ]
    # serialize the first so its prefix pane is stored before the rest
    h0 = eng.submit(prompts[0], cases[0])
    eng.run_until_idle()
    handles = [eng.submit(p, sp) for p, sp in zip(prompts[1:], cases[1:])]
    eng.run_until_idle()
    for h, p, sp in zip([h0] + handles, prompts, cases):
        assert h.output_ids == solo_tokens(params, cfg, p, sp), sp
    st = eng.prefix_store.stats()
    assert st["hits"] >= 2 and st["misses"] >= 1
    assert eng.n_recompiles == 0


def test_prefix_hit_skips_cached_span_forward_flops(model):
    """Acceptance: a prefix HIT performs zero prompt-forward FLOPs for
    the cached span. Forward-call spy on the chunk program: request 2's
    40-token cached span costs 0 chunk calls — only its suffix chunks
    run — and the monolithic prefill program is never called at all."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=128,
                       warmup_prompt_cap=64, kv_policy=PREFIXED)
    eng.warmup()
    calls = {"chunk": 0, "mono": 0}
    real_chunk, real_mono = eng._prefill_chunk, eng._prefill

    def spy_chunk(*a, **kw):
        calls["chunk"] += 1
        return real_chunk(*a, **kw)

    def spy_mono(*a, **kw):
        calls["mono"] += 1
        return real_mono(*a, **kw)

    eng._prefill_chunk, eng._prefill = spy_chunk, spy_mono
    prompts = shared_prefix_prompts(cfg, 2, prefix_len=40)
    sp = SamplingParams(max_new_tokens=2, ignore_eos=True)
    eng.submit(prompts[0], sp)
    eng.run_until_idle()
    miss_chunks = calls["chunk"]
    assert miss_chunks == -(-len(prompts[0]) // 16)  # full prompt chunked
    h2 = eng.submit(prompts[1], sp)
    eng.run_until_idle()
    hit_chunks = calls["chunk"] - miss_chunks
    # cached span = 32 (chunk-aligned part of the 40-token prefix):
    # only the remaining suffix chunks run a forward
    span = eng.prefix_store.storable_span(len(prompts[1]))
    assert hit_chunks == -(-(len(prompts[1]) - span) // 16)
    assert hit_chunks < miss_chunks
    assert calls["mono"] == 0
    assert len(h2.output_ids) == 2


def test_chunked_coresident_outputs_bit_identical_to_unchunked(model):
    """Chunked prefill bounds tick stalls WITHOUT changing anyone's
    tokens: a short request co-resident with a long-prompt request
    produces bit-identical outputs under chunking vs the monolithic
    engine vs solo generate()."""
    cfg, params = model
    long_p = np.asarray(np.arange(2, 92) % 90 + 2, np.int32)   # 90 tokens
    short_p = np.array([7, 8, 9, 10], np.int32)
    sp_long = SamplingParams(max_new_tokens=6, ignore_eos=True, seed=2)
    sp_short = SamplingParams(max_new_tokens=10, temperature=0.9, top_k=7,
                              ignore_eos=True, seed=4)
    results = {}
    for name, pol in (("mono", KVCachePolicy()), ("chunked", CHUNKED)):
        eng = DecodeEngine(cfg, params, n_slots=2, max_len=128,
                           warmup_prompt_cap=96, kv_policy=pol)
        eng.warmup()
        hs = eng.submit(short_p, sp_short)
        eng.step()                       # short request decodes alone...
        hl = eng.submit(long_p, sp_long)   # ...then the long one arrives
        eng.run_until_idle()
        results[name] = (hs.output_ids, hl.output_ids)
        assert eng.n_recompiles == 0
    assert results["mono"] == results["chunked"]
    assert results["chunked"][0] == solo_tokens(params, cfg, short_p,
                                                sp_short)
    assert results["chunked"][1] == solo_tokens(params, cfg, long_p,
                                                sp_long)


def test_zero_recompiles_across_hit_miss_evict_under_traffic(model):
    """Compile discipline over the store's whole lifecycle: a budget
    sized for ONE pane forces eviction churn while distinct + shared
    prefixes stream through — hits, misses, inserts and evictions all
    run against the frozen program set (zero recompiles)."""
    cfg, params = model
    # one pane = L*(K+V)*Hkv*pane_len*hd*4B; pane_len = bucket(64) = 64
    pane_bytes = cache_nbytes(extract_prefix_panes(
        init_slot_cache(cfg, 1, 128), jnp.asarray(0, jnp.int32),
        jnp.asarray(1, jnp.int32), pane_len=64))
    policy = KVCachePolicy(prefill_chunk=16, prefix_cache=True,
                           prefix_budget_bytes=int(1.5 * pane_bytes))
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=128,
                       warmup_prompt_cap=64, kv_policy=policy)
    eng.warmup()
    sp = SamplingParams(max_new_tokens=2, ignore_eos=True)
    families = [shared_prefix_prompts(cfg, 2, prefix_len=33, seed=s)
                for s in range(3)]
    for wave in range(2):
        for fam in families:
            for p in fam:
                eng.submit(p, sp)
            eng.run_until_idle()
    st = eng.prefix_store.stats()
    assert st["evictions"] >= 1, st
    assert st["hits"] >= 1, st
    assert st["entries"] <= 1              # budget holds one pane
    assert eng.n_recompiles == 0
    assert eng.scheduler.n_active == 0 and len(eng.queue) == 0


def test_adapter_namespaced_prefix_and_reload_invalidation(model,
                                                           tmp_path):
    """Per-tenant prefix namespacing: the same system prompt cached
    under adapter A is NOT served to base traffic (the panes embed A's
    deltas), and an evict+reload of A gets a fresh load tag so the old
    install's panes stop matching."""
    from building_llm_from_scratch_tpu.models.lora import (
        init_lora_params,
        save_adapter,
    )
    from building_llm_from_scratch_tpu.serving.adapters import (
        AdapterRegistry,
    )

    cfg, params = model
    art = str(tmp_path / "a.npz")
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(7), rank=2)
    save_adapter(art, lora, rank=2, alpha=4.0, cfg=cfg)
    reg = AdapterRegistry(cfg, params, capacity=2, max_rank=2)
    reg.load("ta", art)
    assert reg.load_tag("ta") == "ta#1"
    eng = DecodeEngine(cfg, params, n_slots=1, max_len=128,
                       warmup_prompt_cap=64, kv_policy=PREFIXED,
                       adapters=reg)
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 2)
    sp_a = SamplingParams(max_new_tokens=2, ignore_eos=True, adapter="ta")
    sp_b = SamplingParams(max_new_tokens=2, ignore_eos=True)
    eng.submit(prompts[0], sp_a)
    eng.run_until_idle()
    # base traffic over the same prefix: MISS (namespace differs)
    eng.submit(prompts[1], sp_b)
    eng.run_until_idle()
    st = eng.prefix_store.stats()
    assert st["hits"] == 0 and st["misses"] == 2
    # same tenant again: HIT
    eng.submit(prompts[1], sp_a)
    eng.run_until_idle()
    assert eng.prefix_store.stats()["hits"] == 1
    # reload invalidates: fresh tag, old pane unreachable
    reg.evict("ta")
    assert reg.load_tag("ta") is None
    reg.load("ta", art)
    assert reg.load_tag("ta") == "ta#2"
    eng.submit(prompts[0], sp_a)
    eng.run_until_idle()
    st = eng.prefix_store.stats()
    # three misses total: the tenant's first request, the base-traffic
    # probe, and the post-reload request (old ta#1 pane unreachable)
    assert st["hits"] == 1 and st["misses"] == 3
    assert eng.n_recompiles == 0


def test_coadmitted_sharers_catch_up_within_run(model):
    """Co-admitted requests sharing a prefix (first wave, empty store)
    don't all recompute it: early insertion + the mid-prefill catch-up
    probe let the co-residents jump ahead on the first sharer's pane
    (late hits), and every request still matches its solo run."""
    cfg, params = model
    eng = DecodeEngine(cfg, params, n_slots=4, max_len=128,
                       warmup_prompt_cap=64, kv_policy=PREFIXED)
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 4)
    sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=9)
    handles = [eng.submit(p, sp) for p in prompts]
    eng.run_until_idle()
    for h, p in zip(handles, prompts):
        assert h.output_ids == solo_tokens(params, cfg, p, sp)
    st = eng.prefix_store.stats()
    assert st["hits"] >= 3, st             # late hits caught up
    assert st["misses"] == 4               # all four admitted pre-store
    assert eng.n_recompiles == 0


def test_prefix_telemetry_events_and_gauges(model, tmp_path):
    """Satellite: prefix_hit/miss/insert events land in the JSONL and
    conform to the schema; /metrics exports the hit-ratio and KV
    bytes-per-slot gauges; the warmup event records the policy."""
    from building_llm_from_scratch_tpu.obs.metrics import (
        configure_metrics,
    )
    from building_llm_from_scratch_tpu.obs.schema import validate_event

    cfg, params = model
    mj = str(tmp_path / "kv_metrics.jsonl")
    sink = configure_metrics(mj)
    sink.write_header(test="kvcache")
    try:
        eng = DecodeEngine(cfg, params, n_slots=2, max_len=128,
                           warmup_prompt_cap=64, kv_policy=PREFIXED)
        eng.warmup()
        sp = SamplingParams(max_new_tokens=2, ignore_eos=True)
        for p in shared_prefix_prompts(cfg, 2):
            eng.submit(p, sp)
            eng.run_until_idle()
        prom = eng.prometheus_text()
    finally:
        sink.close()
        configure_metrics(None)
    rows = [json.loads(line) for line in open(mj)]
    by_kind = {}
    for r in rows:
        if r.get("type") == "event":
            by_kind.setdefault(r["event"], []).append(r)
    assert by_kind.get("prefix_miss") and by_kind.get("prefix_hit")
    assert by_kind.get("prefix_insert")
    for kind in ("prefix_hit", "prefix_miss", "prefix_insert"):
        for e in by_kind[kind]:
            fields = {k: v for k, v in e.items()
                      if k not in ("type", "time", "event", "step")}
            assert validate_event(kind, fields) == [], (kind, e)
    warm = by_kind["serve_warmup"][-1]
    assert warm["prefix_cache"] is True and warm["prefill_chunk"] == 16
    assert warm["kv_quant"] == "model"
    assert "bllm_serve_prefix_hit_ratio" in prom
    assert "bllm_serve_kv_bytes_per_slot" in prom
    assert "bllm_serve_prefix_hits" in prom
    assert "bllm_serve_tick_prefill_seconds_bucket" in prom


def test_prefix_plus_int8_compose(model):
    """The full policy — int8 KV + prefix cache + chunked prefill — in
    one engine: panes store quantized bytes (copy is byte-exact, so a
    hit reproduces the donor's quantized prefix EXACTLY) and traffic
    completes with zero recompiles."""
    cfg, params = model
    policy = KVCachePolicy(kv_quant="int8", prefill_chunk=16,
                           prefix_cache=True, prefix_budget_bytes=8 << 20)
    eng = DecodeEngine(cfg, params, n_slots=2, max_len=128,
                       warmup_prompt_cap=64, kv_policy=policy)
    eng.warmup()
    prompts = shared_prefix_prompts(cfg, 3)
    sp = SamplingParams(max_new_tokens=4, ignore_eos=True, seed=1)
    h0 = eng.submit(prompts[0], sp)
    eng.run_until_idle()
    hs = [eng.submit(p, sp) for p in prompts[1:]]
    eng.run_until_idle()
    for h in [h0] + hs:
        assert h.finish_reason == "length" and len(h.output_ids) == 4
    st = eng.prefix_store.stats()
    assert st["hits"] >= 2
    assert eng.n_recompiles == 0
    # the stored pane is int8 + scales (quantized at source, not re-
    # quantized on copy)
    entry = next(iter(eng.prefix_store._entries.values()))
    assert entry.panes["k"].dtype == jnp.int8
    assert entry.panes["k_scale"].dtype == jnp.float32

    # int8 tokens may differ from the fp32 reference within tolerance,
    # but a HIT must reproduce the MISS path bit-exactly: same engine,
    # same request, prefix served from cache the second time
    h_again = eng.submit(prompts[0], sp)
    eng.run_until_idle()
    assert h_again.output_ids == h0.output_ids
