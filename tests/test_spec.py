"""Speculative decoding (serving/spec.py + models/transformer.verify_slots
+ the engine's draft-and-verify tick): n-gram drafter units, BIT-parity of
engine tokens spec-on vs spec-off (greedy AND seeded sampling, at 0%,
mixed and ~100% acceptance), zero recompiles across acceptance churn
under the frozen watcher, per-request opt-out, composition with int8 KV
and chunked prefill, the near-capacity position clamp, and the
acceptance telemetry."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import init_params
from building_llm_from_scratch_tpu.obs.metrics import configure_metrics
from building_llm_from_scratch_tpu.serving import (
    DecodeEngine,
    Drafter,
    KVCachePolicy,
    NgramDrafter,
    SamplingParams,
)


def tiny_cfg(ctx=64, **kw):
    base = dict(name="spec-tiny", vocab_size=96, context_length=ctx,
                emb_dim=32, n_heads=2, n_layers=2, hidden_dim=64,
                n_kv_groups=2, norm="layernorm", positional="learned",
                activation="gelu", drop_rate=0.0, eos_id=1)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _mixed_requests(cfg, n=6, max_new=16, prompt_len=8, seed=0):
    """Mixed traffic: greedy and seeded-sampled rows, assorted budgets."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(2, cfg.vocab_size, (prompt_len,)
                            ).astype(np.int32) for _ in range(n)]
    params = [SamplingParams(max_new_tokens=max_new - (i % 3),
                             ignore_eos=True, seed=i,
                             temperature=0.7 if i % 2 else 0.0,
                             top_k=8 if i % 2 else None)
              for i in range(n)]
    return prompts, params


def _run_engine(cfg, params, prompts, sps, *, spec_k=0, drafter=None,
                n_slots=2, kv_policy=None, max_len=None):
    eng = DecodeEngine(cfg, params, n_slots=n_slots,
                       max_queue=len(prompts), warmup_prompt_cap=16,
                       spec_k=spec_k, drafter=drafter,
                       kv_policy=kv_policy, max_len=max_len)
    eng.warmup()
    handles = [eng.submit(p, sp, block=True)
               for p, sp in zip(prompts, sps)]
    eng.run_until_idle()
    outs = [list(h.output_ids) for h in handles]
    reasons = [h.finish_reason for h in handles]
    return eng, handles, outs, reasons


# ---------------------------------------------------------------------------
# Drafter units
# ---------------------------------------------------------------------------

def test_ngram_drafter_matches_most_recent_occurrence():
    d = NgramDrafter(max_n=3, min_n=1)
    # history: ... [7 8 9] 4 ... [7 8 9] 5 ... suffix [7 8 9] -> the MOST
    # RECENT earlier occurrence continues with 5
    hist = np.asarray([7, 8, 9, 4, 1, 7, 8, 9, 5, 6, 7, 8, 9], np.int32)
    np.testing.assert_array_equal(d.propose(hist, 2), [5, 6])


def test_ngram_drafter_prefers_longer_match():
    d = NgramDrafter(max_n=2, min_n=1)
    # suffix [3 4]: bigram occurs at (3,4)->5 earlier; the unigram [4]
    # ALSO occurs later followed by 9 — the longer match must win
    hist = np.asarray([3, 4, 5, 4, 9, 3, 4], np.int32)
    np.testing.assert_array_equal(d.propose(hist, 1), [5])


def test_ngram_drafter_no_match_falls_back_to_last_token():
    d = NgramDrafter(max_n=3, min_n=1)
    hist = np.asarray([10, 11, 12, 13], np.int32)   # all distinct
    np.testing.assert_array_equal(d.propose(hist, 3), [13, 13, 13])


def test_ngram_drafter_history_shorter_than_n():
    d = NgramDrafter(max_n=3, min_n=1)
    # one token: no n-gram (even unigram needs an EARLIER occurrence)
    np.testing.assert_array_equal(d.propose(
        np.asarray([5], np.int32), 2), [5, 5])
    # two tokens, repeated unigram: [5] recurs -> continue with 5
    np.testing.assert_array_equal(d.propose(
        np.asarray([5, 5], np.int32), 2), [5, 5])


def test_ngram_drafter_pads_continuation_off_the_end():
    d = NgramDrafter(max_n=1, min_n=1)
    # unigram [2] matches at index 0; only [8, 2] remain after it — the
    # k=3 draft pads the short continuation with its last token
    hist = np.asarray([2, 8, 2], np.int32)
    np.testing.assert_array_equal(d.propose(hist, 3), [8, 2, 2])


# ---------------------------------------------------------------------------
# Multi-position sampling parity (the verify program's sampling core)
# ---------------------------------------------------------------------------

def test_sample_tokens_multi_rowwise_equals_single_position():
    """Every (slot, position) of the flattened multi-position sampler is
    bit-identical to sample_tokens_dynamic on that row alone — the
    property the exact accept rule stands on."""
    from building_llm_from_scratch_tpu.generate import (
        sample_tokens_dynamic,
        sample_tokens_multi,
        token_rng,
    )

    S, Tq, V = 3, 4, 32
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(S, Tq, V)).astype(np.float32))
    temps = jnp.asarray([0.0, 0.8, 1.3], jnp.float32)
    topks = jnp.asarray([0, 5, 0], jnp.int32)
    base = jax.vmap(jax.random.PRNGKey)(jnp.arange(S))
    offs = jnp.arange(Tq)[None, :] + jnp.asarray([[0], [3], [7]])
    keys = jax.vmap(jax.vmap(token_rng, in_axes=(None, 0)))(base, offs)
    multi = np.asarray(sample_tokens_multi(logits, keys, temps, topks, 8))
    for s in range(S):
        for j in range(Tq):
            one = sample_tokens_dynamic(
                logits[s, j][None], keys[s, j][None], temps[s][None],
                topks[s][None], 8)
            assert int(one[0]) == multi[s, j], (s, j)


# ---------------------------------------------------------------------------
# Engine parity: spec-on tokens == spec-off tokens, bit for bit
# ---------------------------------------------------------------------------

def test_greedy_and_sampled_bit_parity_mixed_traffic(model):
    cfg, params = model
    prompts, sps = _mixed_requests(cfg)
    _, _, ref, ref_r = _run_engine(cfg, params, prompts, sps)
    eng, _, out, out_r = _run_engine(cfg, params, prompts, sps, spec_k=4)
    assert out == ref and out_r == ref_r
    assert eng.n_recompiles == 0


class _OracleDrafter(Drafter):
    """Drafts the TRUE continuation from recorded spec-off sequences —
    forces ~100% acceptance (the other extreme from a never-right
    drafter), so parity is pinned at both acceptance boundaries."""

    def __init__(self, sequences):
        self.sequences = [np.asarray(s, np.int32) for s in sequences]

    def propose(self, history, k):
        L = history.shape[0]
        for seq in self.sequences:
            if L <= seq.shape[0] and np.array_equal(seq[:L], history):
                cont = seq[L: L + k]
                if cont.shape[0] == k:
                    return cont
                pad = np.full((k - cont.shape[0],),
                              history[-1], np.int32)
                return np.concatenate([cont, pad])
        return super().propose(history, k)


class _WrongDrafter(Drafter):
    """Never drafts anything useful (constant token): ~0% acceptance."""

    def propose(self, history, k):
        return np.full((k,), 3, np.int32)


def test_parity_pinned_at_acceptance_extremes(model):
    """Rejection-sampling/argmax acceptance preserves the token stream
    EXACTLY whatever the drafter proposes: an oracle drafter (~full
    acceptance) and a useless one (~zero) both reproduce the
    non-speculative engine bit-for-bit, greedy and sampled rows alike."""
    cfg, params = model
    prompts, sps = _mixed_requests(cfg)
    ref_eng, ref_h, ref, _ = _run_engine(cfg, params, prompts, sps)
    full = [np.concatenate([p, np.asarray(o, np.int32)])
            for p, o in zip(prompts, ref)]

    eng_hi, _, out_hi, _ = _run_engine(cfg, params, prompts, sps,
                                       spec_k=4,
                                       drafter=_OracleDrafter(full))
    assert out_hi == ref
    hi = eng_hi.stats()
    assert hi["spec_tokens_accepted"] > hi["spec_tokens_drafted"] * 0.5

    eng_lo, _, out_lo, _ = _run_engine(cfg, params, prompts, sps,
                                       spec_k=4,
                                       drafter=_WrongDrafter())
    assert out_lo == ref
    lo = eng_lo.stats()
    assert lo["spec_tokens_accepted"] < lo["spec_tokens_drafted"] * 0.2


class _SwitchableDrafter(Drafter):
    def __init__(self):
        self.inner = _WrongDrafter()

    def propose(self, history, k):
        return self.inner.propose(history, k)


def test_zero_recompiles_across_acceptance_churn(model):
    """Acceptance rate is DATA: one engine serving 0%-acceptance traffic,
    then ~100%-acceptance traffic (drafter swapped mid-life), never
    recompiles — the frozen watcher would report any signature change."""
    cfg, params = model
    prompts, sps = _mixed_requests(cfg)
    _, _, ref, _ = _run_engine(cfg, params, prompts, sps)
    full = [np.concatenate([p, np.asarray(o, np.int32)])
            for p, o in zip(prompts, ref)]

    drafter = _SwitchableDrafter()
    eng = DecodeEngine(cfg, params, n_slots=2, max_queue=len(prompts),
                       warmup_prompt_cap=16, spec_k=4, drafter=drafter)
    eng.warmup()
    assert all(w.frozen for w in eng._watchers())

    handles = [eng.submit(p, sp, block=True)
               for p, sp in zip(prompts, sps)]
    eng.run_until_idle()
    assert [list(h.output_ids) for h in handles] == ref
    low = eng.stats()["spec_tokens_accepted"]

    drafter.inner = _OracleDrafter(full)      # 0% -> ~100% mid-life
    handles = [eng.submit(p, sp, block=True)
               for p, sp in zip(prompts, sps)]
    eng.run_until_idle()
    assert [list(h.output_ids) for h in handles] == ref
    assert eng.stats()["spec_tokens_accepted"] > low
    assert eng.n_recompiles == 0


def test_per_request_spec_optout(model):
    """``SamplingParams(spec=False)`` rows ride the same verify program
    committing one token per tick: identical tokens, zero drafted
    tokens on their ledger, co-resident spec rows unaffected."""
    cfg, params = model
    prompts, sps = _mixed_requests(cfg, n=4)
    sps = [sp if i % 2 else
           SamplingParams(**dict(sp.__dict__, spec=False))
           for i, sp in enumerate(sps)]
    _, _, ref, _ = _run_engine(cfg, params, prompts, sps)
    eng, handles, out, _ = _run_engine(cfg, params, prompts, sps,
                                       spec_k=3)
    assert out == ref
    for i, h in enumerate(handles):
        if i % 2 == 0:
            assert h.spec_drafted == 0 and h.spec_accepted == 0
            assert "spec_drafted" not in h.summary()
        else:
            assert h.spec_drafted > 0
            assert h.summary()["spec_drafted"] == h.spec_drafted


# ---------------------------------------------------------------------------
# Composition: int8 KV, chunked prefill, capacity edge
# ---------------------------------------------------------------------------

def test_spec_composes_with_int8_kv(model):
    """spec x int8: quantize-on-write covers the k+1 candidate panes;
    tokens are bit-identical to the int8 spec-OFF engine (same appended
    values => same codes/scales for every committed position)."""
    cfg, params = model
    prompts, sps = _mixed_requests(cfg)
    pol = KVCachePolicy(kv_quant="int8")
    _, _, ref, _ = _run_engine(cfg, params, prompts, sps, kv_policy=pol)
    eng, _, out, _ = _run_engine(cfg, params, prompts, sps, spec_k=4,
                                 kv_policy=KVCachePolicy(kv_quant="int8"))
    assert out == ref
    assert eng.n_recompiles == 0


def test_spec_composes_with_chunked_prefill(model):
    """spec x chunked prefill: mid-prefill slots ride the verify program
    as ignored rows (their garbage appends land at the next chunk's
    write offset exactly as in the plain decode tick); co-resident
    outputs stay bit-identical to the chunked spec-off engine."""
    cfg, params = model
    prompts, sps = _mixed_requests(cfg, prompt_len=20)
    pol = lambda: KVCachePolicy(prefill_chunk=8)  # noqa: E731
    _, _, ref, _ = _run_engine(cfg, params, prompts, sps,
                               kv_policy=pol())
    eng, _, out, _ = _run_engine(cfg, params, prompts, sps, spec_k=4,
                                 kv_policy=pol())
    assert out == ref
    assert eng.n_recompiles == 0


def test_spec_composes_with_adapters(model, tmp_path):
    """spec x multi-tenant LoRA: the verify program carries the adapter
    pool exactly like the decode step (gathered per-row application over
    all k+1 positions); mixed adapter+base traffic stays bit-identical
    to the spec-off adapter engine with zero recompiles."""
    from building_llm_from_scratch_tpu.models.lora import (
        init_lora_params,
        save_adapter,
    )
    from building_llm_from_scratch_tpu.serving import AdapterRegistry

    cfg, params = model
    lora = init_lora_params(cfg, params, jax.random.PRNGKey(5), rank=4)
    lora = jax.tree_util.tree_map(lambda a: a + 0.02, lora)
    art = str(tmp_path / "a.npz")
    save_adapter(art, lora, rank=4, alpha=8, cfg=cfg)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(2, cfg.vocab_size, (8,)).astype(np.int32)
               for _ in range(4)]
    sps = [SamplingParams(max_new_tokens=12, ignore_eos=True, seed=i,
                          temperature=0.6 if i >= 2 else 0.0,
                          top_k=8 if i >= 2 else None,
                          adapter="a" if i % 2 else None)
           for i in range(4)]

    def run(spec_k):
        reg = AdapterRegistry.from_artifacts(cfg, params, {"a": art})
        eng = DecodeEngine(cfg, params, n_slots=2, max_queue=4,
                           warmup_prompt_cap=16, adapters=reg,
                           spec_k=spec_k)
        eng.warmup()
        hs = [eng.submit(p, sp, block=True)
              for p, sp in zip(prompts, sps)]
        eng.run_until_idle()
        return [list(h.output_ids) for h in hs], eng.n_recompiles

    ref, _ = run(0)
    out, recompiles = run(4)
    assert out == ref
    assert recompiles == 0


def test_near_capacity_rows_complete_with_parity(model):
    """Regression: rows decoding at the slot-capacity edge. The verify
    program's tail positions exceed context_length there — unclamped
    they would index NaN positional rows (jnp.take OOB fill) and the
    0*NaN value einsum poisoned the whole row into a non_finite_logits
    retirement. Clamped, capacity-edge requests complete bit-identically
    to spec-off."""
    cfg, params = model
    rng = np.random.default_rng(1)
    max_len = 32
    prompts = [rng.integers(2, cfg.vocab_size, (8,)).astype(np.int32)
               for _ in range(3)]
    sps = [SamplingParams(max_new_tokens=max_len - 8, ignore_eos=True,
                          seed=i, temperature=0.5 if i == 2 else 0.0,
                          top_k=8 if i == 2 else None)
           for i in range(3)]
    _, _, ref, ref_r = _run_engine(cfg, params, prompts, sps,
                                   max_len=max_len)
    assert ref_r == ["length"] * 3
    eng, _, out, out_r = _run_engine(cfg, params, prompts, sps,
                                     spec_k=4, max_len=max_len)
    assert out_r == ["length"] * 3
    assert out == ref
    assert eng.n_recompiles == 0


def test_spec_k_bounds_validated(model):
    cfg, params = model
    with pytest.raises(ValueError, match="spec_k"):
        DecodeEngine(cfg, params, n_slots=1, spec_k=-1)
    with pytest.raises(ValueError, match="spec_k"):
        DecodeEngine(cfg, params, n_slots=1, max_len=8, spec_k=8)


# ---------------------------------------------------------------------------
# Telemetry
# ---------------------------------------------------------------------------

def test_acceptance_telemetry_lands_everywhere(model, tmp_path):
    """request_done carries the per-request draft/accept ledger, cadence
    metrics rows carry per-window drafted/accepted, /metrics exposes the
    cumulative counters + acceptance-ratio gauge, and serve_warmup
    records the spec config."""
    from building_llm_from_scratch_tpu.obs.schema import validate_event

    cfg, params = model
    # the spec-off reference runs BEFORE the sink attaches — only the
    # speculative engine's telemetry lands in the JSONL under test
    prompts, sps = _mixed_requests(cfg)
    _, _, ref, _ = _run_engine(cfg, params, prompts, sps)
    full = [np.concatenate([p, np.asarray(o, np.int32)])
            for p, o in zip(prompts, ref)]
    jsonl = tmp_path / "metrics.jsonl"
    configure_metrics(str(jsonl))
    try:
        eng = DecodeEngine(cfg, params, n_slots=2,
                           max_queue=len(prompts), warmup_prompt_cap=16,
                           spec_k=4, drafter=_OracleDrafter(full),
                           metrics_every=2)
        eng.warmup()
        handles = [eng.submit(p, sp, block=True)
                   for p, sp in zip(prompts, sps)]
        eng.run_until_idle()
        stats = eng.stats()
        text = eng.prometheus_text()
        eng.shutdown()
    finally:
        configure_metrics(None)

    rows = [json.loads(line) for line in open(jsonl)]
    warm = [r for r in rows if r.get("event") == "serve_warmup"]
    assert warm[-1]["spec_k"] == 4
    assert "drafter" in warm[-1]
    done = [r for r in rows if r.get("event") == "request_done"]
    assert len(done) == len(prompts)
    assert all(r["spec_drafted"] > 0 for r in done)
    assert sum(r["spec_accepted"] for r in done) > 0
    for r in done:
        fields = {k: v for k, v in r.items()
                  if k not in ("type", "time", "event")}
        assert validate_event("request_done", fields) == []
    cadence = [r for r in rows if r.get("type") == "metrics"
               and "spec_drafted" in r]
    assert cadence and any(r["spec_accepted"] > 0 for r in cadence)
    # stats + /metrics
    assert stats["spec_tokens_drafted"] > 0
    assert stats["spec_acceptance_ratio"] > 0.5
    assert "bllm_serve_spec_tokens_drafted" in text
    assert "bllm_serve_spec_acceptance_ratio" in text
    # the draft phase is accounted (spec engines do host drafting work)
    assert "bllm_serve_tick_draft_seconds" in text
