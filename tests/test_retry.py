"""Bounded-retry policy tests (utils/retry.py): transient vs definitive
error classification, exponential backoff + jitter, attempt bounds, and
the wiring into the HF fetch paths."""

import pytest

from building_llm_from_scratch_tpu.utils.retry import (
    is_retryable_fetch_error,
    with_retries,
)


class EntryNotFoundError(Exception):
    """Name-matched stand-in for huggingface_hub's 404 error."""


class _Resp:
    def __init__(self, status_code):
        self.status_code = status_code


class HTTPError(Exception):
    def __init__(self, status):
        super().__init__(f"http {status}")
        self.response = _Resp(status)


def test_classification():
    assert is_retryable_fetch_error(ConnectionError("reset"))
    assert is_retryable_fetch_error(TimeoutError())
    assert is_retryable_fetch_error(OSError("socket closed"))
    assert is_retryable_fetch_error(HTTPError(503))
    assert is_retryable_fetch_error(HTTPError(429))
    # definitive answers: retrying only delays the real error
    assert not is_retryable_fetch_error(EntryNotFoundError("404"))
    assert not is_retryable_fetch_error(HTTPError(404))
    assert not is_retryable_fetch_error(HTTPError(401))
    assert not is_retryable_fetch_error(FileNotFoundError("local"))
    assert not is_retryable_fetch_error(ValueError("bug"))


def test_retries_transient_then_succeeds():
    calls, delays = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("reset")
        return "asset"

    out = with_retries(flaky, sleep=delays.append, rng=lambda: 0.0)
    assert out == "asset" and len(calls) == 3
    assert delays == [1.0, 2.0]              # exponential, jitter=0 here


def test_jitter_added_to_backoff():
    delays = []

    def flaky():
        if len(delays) < 1:
            raise TimeoutError()
        return 1

    with_retries(flaky, sleep=delays.append, rng=lambda: 1.0)
    assert delays == [2.0]                   # base 1.0 + 100% jitter


def test_gives_up_after_attempts_and_reraises_original():
    calls = []

    def always_down():
        calls.append(1)
        raise ConnectionError("still down")

    with pytest.raises(ConnectionError, match="still down"):
        with_retries(always_down, attempts=3, sleep=lambda _: None)
    assert len(calls) == 3


def test_definitive_error_fails_fast():
    calls = []

    def not_found():
        calls.append(1)
        raise EntryNotFoundError("no such repo")

    with pytest.raises(EntryNotFoundError):
        with_retries(not_found, sleep=lambda _: None)
    assert len(calls) == 1                   # no retry on a 404-shaped error


def test_fetch_paths_route_through_retry(monkeypatch, tmp_path):
    """weights/fetch._resolve_files and tokenizers.fetch_tokenizer_asset
    survive two transient hub failures."""
    import sys
    import types

    from building_llm_from_scratch_tpu.data import tokenizers
    from building_llm_from_scratch_tpu.weights import fetch

    calls = []

    def fake_download(repo_id, filename, cache_dir):
        calls.append(filename)
        if len(calls) % 3 != 0:
            raise ConnectionError("flaky hub")
        return f"/cache/{filename}"

    fake_hub = types.SimpleNamespace(hf_hub_download=fake_download)
    monkeypatch.setitem(sys.modules, "huggingface_hub", fake_hub)
    monkeypatch.setattr("building_llm_from_scratch_tpu.utils.retry.time",
                        types.SimpleNamespace(sleep=lambda _: None))

    got = fetch._resolve_files("org/repo", ["model.safetensors"], None, "c")
    assert got == ["/cache/model.safetensors"] and len(calls) == 3

    calls.clear()
    path = tokenizers.fetch_tokenizer_asset("llama3_2", cache_dir="c")
    assert path.endswith("tokenizer.model") and len(calls) == 3
