"""Cross-process fleet tests (serving/fleet.py + serving/worker.py):
supervised worker SUBPROCESSES behind the engine-shaped ``ProcessFleet``
facade. The fault-injection contract: kill -9 a worker mid-decode under
live traffic and every submitted request either completes or fails with
a TYPED ``worker_dead`` error — zero silently lost, queued work
re-dispatched onto survivors under the SAME ``Request`` handles,
survivors never recompile, the dead worker restarts within its backoff
budget and rejoins dispatch. Plus: ``/healthz`` reports ``degraded``
(never raises) while a worker is down, restart-budget exhaustion
degrades the fleet to survivors instead of flapping, and graceful drain
hands the retiring worker's prefix panes to the adoptee BYTE-IDENTICAL.

All fleet tests run the jax-free ``FakeEngine`` (``spec.fake``) so each
worker process boots in ~a second; the real-engine path is covered by
``scripts/ci_quick.sh``'s CLI smoke and ``bench.py serve_fleet``'s
cross-process arm."""

import json
import os
import signal
import time

import numpy as np
import pytest

from building_llm_from_scratch_tpu.obs import configure_metrics
from building_llm_from_scratch_tpu.serving import (
    EngineSpec,
    ProcessFleet,
    SamplingParams,
)

@pytest.fixture
def sink(tmp_path):
    path = tmp_path / "metrics.jsonl"
    logger = configure_metrics(str(path), run_metadata={"test": True})
    yield str(path)
    logger.close()
    configure_metrics(None)


def load_events(path):
    rows = [json.loads(line) for line in open(path)]
    return [r for r in rows if r.get("type") == "event"]


def fake_spec(**fake_kw):
    fake = dict(n_slots=2, max_queue=32, tpot_s=0.01,
                default_max_new_tokens=8, vocab_size=96)
    fake.update(fake_kw)
    return EngineSpec(fake=fake)


def make_fleet(n=2, tmp_path=None, spec=None, **kw):
    kw.setdefault("heartbeat_s", 0.1)
    kw.setdefault("heartbeat_timeout_s", 5.0)
    kw.setdefault("max_restarts", 2)
    kw.setdefault("restart_backoff_s", 0.2)
    kw.setdefault("ready_timeout_s", 120.0)
    if tmp_path is not None:
        kw.setdefault("socket_dir", str(tmp_path / "socks"))
        os.makedirs(kw["socket_dir"], exist_ok=True)
    return ProcessFleet(spec or fake_spec(), n, **kw)


def expected_tokens(prompt, n):
    """FakeEngine's deterministic rule: token i = (prompt[-1] + i) % 96
    — identical wherever the request runs, so a re-dispatched handle is
    checkable against the same reference."""
    last = int(prompt[-1])
    return [(last + i) % 96 for i in range(n)]


def wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.slow
def test_fleet_serves_across_processes(tmp_path, sink):
    fleet = make_fleet(2, tmp_path).start()
    try:
        prompts = [np.array([3 + i, 7 + i], np.int32) for i in range(6)]
        handles = [fleet.submit(p, SamplingParams(max_new_tokens=8),
                                block=True, timeout=10.0)
                   for p in prompts]
        for p, h in zip(prompts, handles):
            h.result(timeout=30.0)
            assert h.output_ids == expected_tokens(p, 8)
            assert h.finish_reason == "length"
            assert h.route and "replica" in h.route
        hz = fleet.healthz_payload()
        assert hz["status"] == "serving"
        assert hz["workers_up"] == 2
        assert {r["status"] for r in hz["replicas"]} == {"serving"}
        assert fleet.stats()["requests_finished"] == 6
        assert fleet.n_recompiles == 0
        text = fleet.prometheus_text()
        assert "fleet_workers_up 2" in text
    finally:
        fleet.shutdown(drain=False)
    events = [e["event"] for e in load_events(sink)]
    assert events.count("worker_spawn") == 2
    assert "serve_fleet" in events


@pytest.mark.slow
def test_kill9_mid_decode_zero_lost_typed_failures_restart(tmp_path, sink):
    """The tentpole acceptance test. kill -9 one worker mid-decode with
    a full queue behind it: every handle resolves (zero lost), in-flight
    work fails TYPED with worker_dead, queued work re-dispatches onto
    the survivor under the ORIGINAL handles, the survivor never
    recompiles, and the dead worker restarts and serves again."""
    spec = fake_spec(tpot_s=0.05, n_slots=2)
    fleet = make_fleet(2, tmp_path, spec=spec).start()
    try:
        prompts = [np.array([10 + i], np.int32) for i in range(12)]
        handles = [fleet.submit(p, SamplingParams(max_new_tokens=8),
                                block=True, timeout=10.0)
                   for p in prompts]
        by_id = {h.id: p for h, p in zip(handles, prompts)}
        time.sleep(0.15)                       # let decode start
        hz = fleet.healthz_payload()
        victim_idx = next(r["replica"] for r in hz["replicas"]
                          if r["status"] == "serving")
        victim_pid = fleet.workers[victim_idx].pid
        os.kill(victim_pid, signal.SIGKILL)

        ok, failed, lost = [], [], []
        for h in handles:
            try:
                h.result(timeout=60.0)
                ok.append(h)
            except RuntimeError as e:
                assert "worker_dead" in str(e), (
                    f"death must surface typed, got: {e}")
                failed.append(h)
            except Exception as e:              # noqa: BLE001
                lost.append((h, e))
        assert not lost, f"untypted/lost handles: {lost}"
        assert len(ok) + len(failed) == 12
        assert ok, "survivor should have completed redispatched work"
        for h in ok:                            # same handle, same tokens
            assert h.output_ids == expected_tokens(by_id[h.id], 8)

        st = fleet.stats()
        assert st["worker_deaths"] == 1
        assert st["failed_on_death"] == len(failed)
        assert st["redispatched_total"] >= 1
        assert fleet.n_recompiles == 0, "survivors must not recompile"

        wait_for(lambda: fleet.stats()["worker_restarts"] == 1, 30.0,
                 "the dead worker to restart")
        wait_for(lambda: fleet.healthz_payload()["status"] == "serving",
                 10.0, "fleet to report serving again")
        # the restarted worker is back in dispatch: fill BOTH workers
        # past one worker's slot+queue capacity and everything completes
        p = np.array([55], np.int32)
        post = [fleet.submit(p, SamplingParams(max_new_tokens=4),
                             block=True, timeout=10.0) for _ in range(8)]
        for h in post:
            h.result(timeout=30.0)
            assert h.output_ids == expected_tokens(p, 4)
    finally:
        fleet.shutdown(drain=False)

    events = load_events(sink)
    kinds = [e["event"] for e in events]
    assert "worker_dead" in kinds
    assert "worker_restart" in kinds
    assert "router_redispatch" in kinds
    dead = next(e for e in events if e["event"] == "worker_dead")
    assert dead["replica"] == victim_idx
    assert dead["pid"] == victim_pid
    restart = next(e for e in events if e["event"] == "worker_restart")
    assert restart["replica"] == victim_idx
    assert restart["restarts"] == 1


@pytest.mark.slow
def test_healthz_degraded_during_outage_and_never_raises(tmp_path, sink):
    fleet = make_fleet(2, tmp_path,
                       restart_backoff_s=1.0).start()   # slow restart:
    try:                                     # a wide window to observe
        os.kill(fleet.workers[0].pid, signal.SIGKILL)
        wait_for(lambda: fleet.healthz_payload()["status"] == "degraded",
                 10.0, "degraded health after kill")
        # health is built from cached snapshots — no RPC, so hammering
        # it during the outage can neither raise nor stall
        t0 = time.monotonic()
        for _ in range(50):
            hz = fleet.healthz_payload()
            assert hz["status"] in ("degraded", "serving")
        assert time.monotonic() - t0 < 1.0
        row = next(r for r in hz["replicas"] if r["replica"] == 0)
        assert row["status"] in ("restarting", "serving")
        # the survivor keeps serving while its neighbor is down
        h = fleet.submit(np.array([5], np.int32),
                         SamplingParams(max_new_tokens=4), block=True,
                         timeout=10.0)
        h.result(timeout=30.0)
        wait_for(lambda: fleet.healthz_payload()["status"] == "serving",
                 30.0, "restarted worker to rejoin")
        assert fleet.healthz_payload()["workers_up"] == 2
    finally:
        fleet.shutdown(drain=False)


@pytest.mark.slow
def test_restart_budget_exhaustion_degrades_to_survivors(tmp_path, sink):
    fleet = make_fleet(2, tmp_path, max_restarts=0).start()
    try:
        os.kill(fleet.workers[0].pid, signal.SIGKILL)
        wait_for(lambda: fleet.workers[0].stopped, 10.0,
                 "budget-exhausted worker marked stopped")
        hz = fleet.healthz_payload()
        assert hz["status"] == "degraded"
        assert next(r for r in hz["replicas"]
                    if r["replica"] == 0)["status"] == "dead"
        assert fleet.stats()["worker_restarts"] == 0
        # degraded, not down: the survivor serves indefinitely
        for _ in range(3):
            h = fleet.submit(np.array([9], np.int32),
                             SamplingParams(max_new_tokens=4),
                             block=True, timeout=10.0)
            h.result(timeout=30.0)
        time.sleep(0.5)                       # no flapping restarts
        assert fleet.stats()["worker_restarts"] == 0
    finally:
        fleet.shutdown(drain=False)
    assert "worker_restart" not in [e["event"] for e in load_events(sink)]


@pytest.mark.slow
def test_pane_handoff_byte_identical_and_adoptee_hits(tmp_path, sink):
    """Drain a worker that accumulated prefix panes: the survivor must
    import them byte-for-byte (keys are config-fingerprinted, identical
    across same-spec workers) and then serve the shared prefix as a
    prefix_hit — no recompute."""
    spec = fake_spec(prefix_chunk=4)
    fleet = make_fleet(2, tmp_path, spec=spec).start()
    try:
        shared = np.arange(8, dtype=np.int32)        # two full chunks
        for tail in (91, 92, 93):
            h = fleet.submit(np.concatenate([shared, [tail]]).astype(
                np.int32), SamplingParams(max_new_tokens=2),
                block=True, timeout=10.0)
            h.result(timeout=30.0)
        donor = next(i for i in range(2)
                     if (fleet.workers[i].ctrl.call("stats")
                         .get("prefix_store", {}).get("entries", 0)))
        adoptee = 1 - donor
        exported = fleet.workers[donor].ctrl.call("export_panes")
        assert exported["entries"], "donor accumulated no panes"
        before = fleet.workers[adoptee].ctrl.call("stats").get(
            "prefix_store", {})

        out = fleet.drain_worker(donor, timeout=10.0, handoff_to=adoptee)
        assert out["drained"]

        got = fleet.workers[adoptee].ctrl.call("export_panes")
        by_key = {e["key"]: e for e in got["entries"]}
        for ent in exported["entries"]:
            twin = by_key.get(ent["key"])
            assert twin is not None, f"entry {ent['key']} not adopted"
            assert twin["panes"] == ent["panes"], (
                "pane bytes changed in transit")   # b64 equality = bytes
            assert twin["span"] == ent["span"]

        # adoptee now serves the donor's prefix: hit, not recompute
        hits0 = fleet.workers[adoptee].ctrl.call("stats")[
            "prefix_store"]["hits"]
        h = fleet.submit(np.concatenate([shared, [94]]).astype(np.int32),
                         SamplingParams(max_new_tokens=2), block=True,
                         timeout=10.0)
        h.result(timeout=30.0)
        after = fleet.workers[adoptee].ctrl.call("stats")["prefix_store"]
        assert after["hits"] == hits0 + 1
        assert after["misses"] == before.get("misses", 0), (
            "adopted prefix must not be recomputed as a miss")
    finally:
        fleet.shutdown(drain=False)
    events = load_events(sink)
    hand = [e for e in events if e["event"] == "pane_handoff"]
    assert len(hand) == 1
    assert hand[0]["from_replica"] == donor
    assert hand[0]["to_replica"] == adoptee
    assert hand[0]["imported"] == len(exported["entries"])
    assert hand[0]["bytes"] > 0


@pytest.mark.slow
def test_rolling_drain_completes_queued_work(tmp_path, sink):
    fleet = make_fleet(2, tmp_path).start()
    try:
        p = np.array([40], np.int32)
        handles = [fleet.submit(p, SamplingParams(max_new_tokens=6),
                                block=True, timeout=10.0)
                   for _ in range(8)]
        out = fleet.drain(timeout=20.0)
        assert out["seconds"] < 20.0
        for h in handles:                      # drain loses nothing
            h.result(timeout=30.0)
            assert h.output_ids == expected_tokens(p, 6)
        assert fleet.draining
        with pytest.raises(Exception):
            fleet.submit(p, SamplingParams(max_new_tokens=2))
    finally:
        fleet.shutdown(drain=False)


@pytest.mark.slow
def test_shutdown_fails_leftovers_instead_of_hanging(tmp_path, sink):
    spec = fake_spec(tpot_s=0.2)              # slow: work still queued
    fleet = make_fleet(1, tmp_path, spec=spec).start()
    h = fleet.submit(np.array([1], np.int32),
                     SamplingParams(max_new_tokens=64), block=True,
                     timeout=10.0)
    fleet.shutdown(drain=False)
    assert h.done
    with pytest.raises(Exception):
        h.result(timeout=1.0)


def test_stray_serve_workers_flag_guarded():
    from building_llm_from_scratch_tpu.args import get_args

    with pytest.raises(ValueError, match="serve_workers"):
        get_args(["--data_dir", "/tmp", "--serve_workers", "2"])


def test_serve_workers_arg_validation():
    from building_llm_from_scratch_tpu.args import get_args

    base = ["--data_dir", "/tmp", "--mode", "serve",
            "--serve_port", "8080", "--serve_workers", "2"]
    args = get_args(base)
    assert args.serve_workers == 2
    with pytest.raises(ValueError, match="serve_replicas"):
        get_args(base + ["--serve_replicas", "2"])
    with pytest.raises(ValueError, match="load_weights"):
        get_args(base + ["--load_weights"])


def test_engine_spec_json_roundtrip():
    spec = EngineSpec(model="GPT2", size="355M", dtype="fp32", seed=7,
                      tokenizer="byte", tp=2,
                      engine={"n_slots": 4, "max_len": 128},
                      kv_policy={"prefix_cache": True},
                      adapters={"a": "/tmp/a.npz"}, spec_k=3)
    back = EngineSpec.from_json(spec.to_json())
    assert back == spec
