"""Model-level tests: shapes, determinism, remat, KV-cache parity, configs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from building_llm_from_scratch_tpu.configs import (
    ModelConfig,
    get_config,
    get_config_gpt2,
    get_config_llama,
    rescale_theta,
)
from building_llm_from_scratch_tpu.models import (
    build_model,
    forward,
    forward_with_cache,
    init_cache,
    init_params,
)


def tiny_gpt2(**kw):
    return get_config("GPT2", "124M", debug=True, **kw)


def tiny_llama(**kw):
    return get_config("llama3_2", "1B", debug=True, **kw)


@pytest.mark.parametrize("cfg_fn", [tiny_gpt2, tiny_llama])
def test_forward_shapes(cfg_fn, rng_key):
    cfg = cfg_fn()
    params = init_params(cfg, rng_key)
    tokens = jnp.zeros((2, cfg.context_length), jnp.int32)
    logits = forward(params, cfg, tokens)
    assert logits.shape == (2, cfg.context_length, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_remat_matches_plain(rng_key):
    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    tokens = jax.random.randint(rng_key, (2, 8), 0, cfg.vocab_size)
    plain = forward(params, cfg, tokens)
    ckpt = forward(params, cfg.replace(use_actv_ckpt=True), tokens)
    np.testing.assert_allclose(np.asarray(plain), np.asarray(ckpt),
                               rtol=1e-5, atol=1e-5)


def test_remat_gradients_match(rng_key):
    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    tokens = jax.random.randint(rng_key, (2, 8), 0, cfg.vocab_size)

    def loss(p, c):
        return jnp.mean(forward(p, c, tokens) ** 2)

    g1 = jax.grad(loss)(params, cfg)
    g2 = jax.grad(loss)(params, cfg.replace(use_actv_ckpt=True))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-4), g1, g2)


def test_dropout_deterministic_flag(rng_key):
    cfg = tiny_gpt2()
    assert cfg.drop_rate > 0
    params = init_params(cfg, rng_key)
    tokens = jax.random.randint(rng_key, (2, 8), 0, cfg.vocab_size)
    a = forward(params, cfg, tokens)
    b = forward(params, cfg, tokens)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training mode with different rngs differs
    r1 = forward(params, cfg, tokens, rng=jax.random.PRNGKey(1),
                 deterministic=False)
    r2 = forward(params, cfg, tokens, rng=jax.random.PRNGKey(2),
                 deterministic=False)
    assert not np.allclose(np.asarray(r1), np.asarray(r2))


def test_kv_cache_decode_matches_full_forward(rng_key):
    """Prefill + per-token decode must reproduce the uncached forward —
    the correctness condition the reference sidesteps by never caching
    (generate.py:36-45)."""
    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    T = 12
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, T), 0,
                                cfg.vocab_size)
    full = forward(params, cfg, tokens)

    cache = init_cache(cfg, batch_size=2, max_length=16)
    # prefill on the first 6 tokens, then decode 1-by-1
    logits_p, cache = forward_with_cache(params, cfg, tokens[:, :6], cache)
    outs = [logits_p]
    for t in range(6, T):
        step_logits, cache = forward_with_cache(params, cfg,
                                                tokens[:, t:t + 1], cache)
        outs.append(step_logits)
    cached = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(cached),
                               rtol=2e-3, atol=2e-3)


def test_gpt2_learned_positions_used(rng_key):
    cfg = tiny_gpt2()
    params = init_params(cfg, rng_key)
    # same token at different positions must produce different logits
    tokens = jnp.full((1, 4), 7, jnp.int32)
    logits = forward(params, cfg, tokens)
    assert not np.allclose(np.asarray(logits[0, 0]), np.asarray(logits[0, 3]))


def test_param_count_formula_matches_tree(rng_key):
    from building_llm_from_scratch_tpu.utils.memory import count_params

    for cfg in [tiny_gpt2(), tiny_llama(), tiny_gpt2(qkv_bias=True)]:
        params = init_params(cfg, rng_key)
        assert count_params(params) == cfg.num_params()


def test_gpt2_config_registry():
    cfg = get_config_gpt2("355M")
    assert (cfg.emb_dim, cfg.n_heads, cfg.n_layers) == (1024, 16, 24)
    assert cfg.vocab_size == 50257 and cfg.context_length == 1024
    with pytest.raises(ValueError):
        get_config_gpt2("999M")


def test_llama_config_clamp_and_theta_rescale():
    # default: reference behavior — clamp to 1024 w/ linear theta rescale
    cfg = get_config_llama("8B", "llama3")
    assert cfg.context_length == 1024
    assert np.isclose(cfg.rope_base, rescale_theta(500_000.0, 8192, 1024))
    # parameterized escape hatch: keep native context
    cfg_native = get_config_llama("8B", "llama3", target_context_length=None)
    assert cfg_native.context_length == 8192
    assert cfg_native.rope_base == 500_000.0
    # registry must NOT be mutated (reference defect §2.3 #5)
    again = get_config_llama("8B", "llama3")
    assert np.isclose(again.rope_base, cfg.rope_base)


def test_llama2_has_eos():
    # reference defect §2.3 #4: llama2 config lacked eos; ours must not
    cfg = get_config_llama("7B", "llama2")
    assert cfg.eos_id == 2 and cfg.eos_text == "</s>"


def test_build_model_factory():
    cfg, params = build_model("GPT2", "124M", debug=True)
    assert cfg.n_layers == 2
    assert "pos_emb" in params
    cfg2, params2 = build_model("llama3_2", "1B", debug=True)
    assert "pos_emb" not in params2
    assert "gate" in params2["blocks"]["mlp"]


def test_gpt2_124M_param_count_full_size():
    # GPT-2 124M with untied head: ~163M total params (124M backbone +
    # 38.6M untied head), matching the reference's GPTModel layout.
    cfg = get_config_gpt2("124M")
    n = cfg.num_params()
    assert 160e6 < n < 170e6


@pytest.mark.xfail(
    not hasattr(jax, "shard_map"),
    reason="KV-cache vs dense-forward greedy argmax parity diverges on "
           "this older jax CPU backend (reduction-order sensitive on an "
           "untrained model); passes on current jax",
    strict=False)
def test_bucketed_generate_greedy_matches_dense_loop(rng_key):
    """generate() pads the prompt to a shape bucket and resets the cache
    length to the REAL prompt length — greedy output must equal the naive
    full-forward re-run per token (reference semantics, generate.py:36-73)
    for prompt lengths off the bucket boundary."""
    from building_llm_from_scratch_tpu.generate import generate

    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    for Tp in (5, 9):
        prompt = np.asarray(jax.random.randint(
            jax.random.PRNGKey(Tp), (2, Tp), 0, cfg.vocab_size), np.int32)
        out = generate(params, cfg, prompt, max_new_tokens=7,
                       context_size=cfg.context_length)
        ids = prompt.copy()
        for _ in range(7):
            logits = forward(params, cfg, jnp.asarray(ids))[:, -1]
            nxt = np.asarray(jnp.argmax(logits, axis=-1))
            ids = np.concatenate([ids, nxt[:, None].astype(ids.dtype)], 1)
        np.testing.assert_array_equal(np.asarray(out), ids)


def test_generate_eos_stop_quirk(rng_key):
    """All-rows-eos stops WITHOUT appending the triggering token
    (reference generate.py:68-73)."""
    from building_llm_from_scratch_tpu.generate import generate

    cfg = tiny_llama()
    params = init_params(cfg, rng_key)
    # two IDENTICAL rows: greedy emits the same first token on both by
    # construction, so the all-rows-eos condition is guaranteed to trigger
    row = np.asarray(jax.random.randint(
        jax.random.PRNGKey(1), (1, 6), 0, cfg.vocab_size), np.int32)
    prompt = np.concatenate([row, row], axis=0)
    probe = generate(params, cfg, prompt, max_new_tokens=1,
                     context_size=cfg.context_length)
    first = np.asarray(probe)[:, -1]
    assert first[0] == first[1]
    out = generate(params, cfg, prompt, max_new_tokens=5,
                   context_size=cfg.context_length,
                   eos_id=int(first[0]))
    assert out.shape[1] == prompt.shape[1]         # nothing appended
