"""Dataset-acquisition layer (reference L7: Datasets/Gutenberg, Datasets/
Alpaca) on synthetic files — no network."""

import json
import os

import numpy as np
import pytest

from building_llm_from_scratch_tpu.datasets import (
    fetch_alpaca,
    is_english,
    pack_files,
    strip_gutenberg_boilerplate,
)
from building_llm_from_scratch_tpu.datasets.alpaca import main as alpaca_main
from building_llm_from_scratch_tpu.datasets.gutenberg import (
    EOT,
    clean_book,
    find_txt_files,
    main as gutenberg_main,
)

PG_BOOK = """The Project Gutenberg eBook of Test Book
This header is license boilerplate that must not reach training.

*** START OF THE PROJECT GUTENBERG EBOOK TEST BOOK ***

Chapter 1.

It was the best of times, it was the worst of times.


And then   some    more prose across blank lines.

*** END OF THE PROJECT GUTENBERG EBOOK TEST BOOK ***

This footer is also license boilerplate.
"""


def test_is_english_ascii_ratio():
    assert is_english("plain english text " * 10)
    assert not is_english("世界" * 50)          # CJK
    assert not is_english("")


def test_strip_boilerplate_cuts_header_and_footer():
    body = strip_gutenberg_boilerplate(PG_BOOK)
    assert "Chapter 1." in body
    assert "best of times" in body
    assert "license boilerplate" not in body
    assert "START OF" not in body and "END OF" not in body


def test_strip_boilerplate_passthrough_without_markers():
    text = "no markers here\njust prose\n"
    assert strip_gutenberg_boilerplate(text) == text


def test_clean_book_squeezes_blank_runs():
    body = clean_book(PG_BOOK)
    assert "\n\n\n" not in body


def test_pack_files_joins_with_eot_and_filters(tmp_path):
    src = tmp_path / "raw"
    src.mkdir()
    (src / "a.txt").write_text(PG_BOOK)
    (src / "b.txt").write_text("An entirely English second book. " * 20)
    (src / "cjk.txt").write_text("世界" * 200)   # filtered out
    out = tmp_path / "out"
    n = pack_files(find_txt_files(str(src)), str(out))
    assert n == 1
    combined = (out / "combined_1.txt").read_text()
    assert combined.count(EOT) == 1                      # 2 books, 1 join
    assert "best of times" in combined
    assert "世界" not in combined


def test_pack_files_splits_at_size_cap(tmp_path):
    src = tmp_path / "raw"
    src.mkdir()
    big = "All work and no play makes Jack a dull boy. " * 30000  # ~1.3MB
    for i in range(3):
        (src / f"book{i}.txt").write_text(big)
    out = tmp_path / "out"
    n = pack_files(find_txt_files(str(src)), str(out), max_size_mb=3)
    assert n == 2                                        # 1.3+1.3 | 1.3
    sizes = sorted(os.path.getsize(out / f"combined_{i + 1}.txt")
                   for i in range(n))
    assert sizes[-1] < 3 * 1024 * 1024


def test_pack_files_latin1_fallback(tmp_path):
    src = tmp_path / "raw"
    src.mkdir()
    (src / "l1.txt").write_bytes(
        ("caf\xe9 prose in latin-1 " * 50).encode("latin1"))
    out = tmp_path / "out"
    assert pack_files(find_txt_files(str(src)), str(out)) == 1


def test_gutenberg_main_end_to_end(tmp_path):
    src = tmp_path / "raw"
    src.mkdir()
    (src / "a.txt").write_text(PG_BOOK)
    out = tmp_path / "data"
    n = gutenberg_main(["--data_dir", str(src), "--output_dir", str(out)])
    assert n == 1 and (out / "combined_1.txt").exists()


RECORDS = [{"instruction": f"say {i}", "input": "", "output": f"{i}"}
            for i in range(25)]


def _mock_urlopen(monkeypatch, payload: bytes):
    import io
    from urllib import request

    class Resp(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    monkeypatch.setattr(request, "urlopen", lambda url: Resp(payload))


def test_fetch_alpaca_downloads_once(tmp_path, monkeypatch):
    _mock_urlopen(monkeypatch, json.dumps(RECORDS).encode())
    path = str(tmp_path / "alpaca.json")
    data = fetch_alpaca(path)
    assert len(data) == 25
    # second call must be served from the cache, not the (now broken) net
    _mock_urlopen(monkeypatch, b"NOT JSON")
    assert len(fetch_alpaca(path)) == 25


def test_fetch_alpaca_rejects_bad_download(tmp_path, monkeypatch):
    _mock_urlopen(monkeypatch, b"<html>rate limited</html>")
    path = str(tmp_path / "alpaca.json")
    with pytest.raises(json.JSONDecodeError):
        fetch_alpaca(path)
    assert not os.path.exists(path)      # bad payload never poisons cache


def test_alpaca_fetch_then_finetune_end_to_end(tmp_path, monkeypatch):
    """Fresh-clone workflow (round-2 VERDICT missing #1): fetch the dataset
    via the module CLI, then run --finetune on it — offline-mocked."""
    from building_llm_from_scratch_tpu.args import get_args
    from building_llm_from_scratch_tpu.main import main as run_main

    _mock_urlopen(monkeypatch, json.dumps(RECORDS).encode())
    data_dir = str(tmp_path / "data")
    path, n = alpaca_main(["--data_dir", data_dir])
    assert n == 25 and os.path.exists(path)

    out = str(tmp_path / "out")
    trainer = run_main(get_args([
        "--data_dir", data_dir, "--output_dir", out,
        "--debug", "--byte_tokenizer", "--n_epochs", "1",
        "--batch_size", "4", "--eval_freq", "1000",
        "--print_sample_iter", "10000", "--save_ckpt_freq", "10000",
        "--warmup_steps", "2", "--finetune", "--dataset", "alpaca",
    ]))
    assert trainer.global_step > 0
    assert np.isfinite(trainer.train_losses[-1] if trainer.train_losses
                       else 0.0)
