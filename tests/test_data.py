"""Data-pipeline tests: windowing, splits, collator masking semantics.

The collator test cross-checks our fixed-shape loss-weight masking against an
independent transcription of the reference's -100/ignore_index collator
(datautils/dataloader_instruction_finetune.py:10-50) to prove loss-set
equivalence.
"""

import numpy as np
import pytest

from building_llm_from_scratch_tpu.data import (
    ByteTokenizer,
    InstructionDataset,
    InstructLoader,
    PretrainDataset,
    PretrainLoader,
    collate_batch,
    format_input,
    format_input_phi,
    make_windows,
)


def test_make_windows_shapes_and_shift():
    ids = np.arange(100)
    x, y = make_windows(ids, max_length=10, stride=10)
    assert x.shape == y.shape == (9, 10)        # needs 10+1 tokens per row
    np.testing.assert_array_equal(y, x + 1)     # targets are shifted inputs
    np.testing.assert_array_equal(x[0], np.arange(10))
    # overlapping stride
    x2, _ = make_windows(ids, max_length=10, stride=5)
    assert x2.shape[0] == 18
    np.testing.assert_array_equal(x2[1], np.arange(5, 15))


def test_make_windows_short_text():
    x, y = make_windows(np.arange(5), max_length=10, stride=10)
    assert x.shape == (0, 10) and y.shape == (0, 10)


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "Hello <|endoftext|> world"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    assert tok.eos_id == 256
    assert ids.count(256) == 1


def test_pretrain_loader_split_and_batches():
    tok = ByteTokenizer()
    text = "abcdefghij" * 300                    # 3000 chars
    loader = PretrainLoader(tok, batch_size=4, max_length=16)
    train_text, val_text = loader.split_text(text)
    assert len(train_text) == 2700 and len(val_text) == 300
    train, val = loader.create_datasets(text)
    batches = list(loader.batches(train, shuffle=True, epoch=0))
    assert len(batches) == loader.num_batches(train)
    for x, y in batches:
        assert x.shape == (4, 16) and y.shape == (4, 16)
        np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
    # epoch reshuffle differs, same epoch reproduces (set_epoch analog)
    b0 = next(iter(loader.batches(train, epoch=0)))[0]
    b0_again = next(iter(loader.batches(train, epoch=0)))[0]
    b1 = next(iter(loader.batches(train, epoch=1)))[0]
    np.testing.assert_array_equal(b0, b0_again)
    assert not np.array_equal(b0, b1)


def test_pretrain_loader_process_sharding():
    """Two processes must see disjoint rows covering the global batch."""
    tok = ByteTokenizer()
    text = "abcdefghij" * 200
    kw = dict(batch_size=2, max_length=16)
    l0 = PretrainLoader(tok, process_index=0, process_count=2, **kw)
    l1 = PretrainLoader(tok, process_index=1, process_count=2, **kw)
    d0, _ = l0.create_datasets(text)
    d1, _ = l1.create_datasets(text)
    b0 = list(l0.batches(d0, epoch=0))
    b1 = list(l1.batches(d1, epoch=0))
    assert len(b0) == len(b1) > 0
    glob = PretrainLoader(tok, batch_size=4, max_length=16)
    dg, _ = glob.create_datasets(text)
    bg = list(glob.batches(dg, epoch=0))
    # each global batch row set == union of the two process shards
    for (x0, _), (x1, _), (xg, _) in zip(b0, b1, bg):
        merged = np.concatenate([x0, x1])
        assert {tuple(r) for r in merged} == {tuple(r) for r in xg}


def test_format_input_templates():
    entry = {"instruction": "Do X.", "input": "with Y", "output": "done"}
    s = format_input(entry)
    assert s.startswith("Below is an instruction")
    assert "### Instruction:\nDo X." in s
    assert "### Input:\nwith Y" in s
    # empty input drops the Input section (reference :24)
    s2 = format_input({"instruction": "Do X.", "input": ""})
    assert "### Input" not in s2
    sp = format_input_phi(entry)
    assert sp == "<|user|>\nDo X.\nwith Y"


def _reference_collate(batch, pad_token_id, allowed_max_length):
    """Independent transcription of the reference collator's semantics
    (dynamic length + -100 sentinels) used as the oracle."""
    import torch

    batch_max = max(len(item) + 1 for _l, item in batch)
    ins, tgs = [], []
    for instr_len, item in batch:
        item = list(item) + [pad_token_id]
        padded = item + [pad_token_id] * (batch_max - len(item))
        inputs = torch.tensor(padded[:-1])
        targets = torch.tensor(padded[1:])
        mask = targets == pad_token_id
        idx = torch.nonzero(mask).squeeze(-1)
        if idx.numel() > 1:
            targets[idx[1:]] = -100
        targets[: instr_len - 1] = -100
        ins.append(inputs[:allowed_max_length])
        tgs.append(targets[:allowed_max_length])
    return torch.stack(ins), torch.stack(tgs)


def test_collate_matches_reference_loss_set():
    """Our (targets, weights) must supervise exactly the token set the
    reference's -100 collator supervises, and the weighted CE must equal
    torch's ignore_index CE."""
    torch = pytest.importorskip("torch")
    pad = 9                                       # pretend eos/pad id
    batch = [
        (3, [1, 2, 3, 4, 5]),                     # normal row
        (2, [6, 7]),                              # short row
        (4, [1, 2, 3, 9, 5, 6]),                  # contains pad id mid-seq
    ]
    T = 8
    ours_in, ours_tg, ours_w = collate_batch(batch, pad_token_id=pad,
                                             allowed_max_length=T)
    ref_in, ref_tg = _reference_collate(batch, pad, T)
    # inputs agree on the reference's (shorter) width; ours pad the rest
    W = ref_in.shape[1]
    np.testing.assert_array_equal(ours_in[:, :W], ref_in.numpy())
    assert (ours_in[:, W:] == pad).all()
    # the supervised set matches: weights==1 <=> ref target != -100
    ref_mask = (ref_tg.numpy() != -100).astype(np.float32)
    np.testing.assert_array_equal(ours_w[:, :W], ref_mask)
    assert (ours_w[:, W:] == 0).all()
    # and the losses agree
    V = 16
    logits = torch.randn(len(batch), T, V)
    ref_loss = torch.nn.functional.cross_entropy(
        logits[:, :W].reshape(-1, V), ref_tg.reshape(-1), ignore_index=-100)
    logp = torch.log_softmax(logits, dim=-1)
    tok_ll = torch.gather(logp, 2, torch.from_numpy(ours_tg).long()
                          .unsqueeze(-1)).squeeze(-1)
    w = torch.from_numpy(ours_w)
    our_loss = -(tok_ll * w).sum() / w.sum()
    assert abs(float(ref_loss) - float(our_loss)) < 1e-6


def test_instruction_dataset_and_loader():
    tok = ByteTokenizer()
    records = [
        {"instruction": f"say {i}", "input": "" if i % 2 else "ctx",
         "output": f"answer {i}"}
        for i in range(20)
    ]
    ds = InstructionDataset(records, tok)
    instr_len, ids = ds[0]
    # prompt tokens are a strict prefix of the full encoding
    assert 0 < instr_len < len(ids)

    loader = InstructLoader(tok, batch_size=4, max_length=256,
                            pad_token_id=tok.eos_id)
    train, val = loader.create_datasets(records)
    assert len(train) == 18 and len(val) == 2
    for x, y, w in loader.batches(train, epoch=0):
        assert x.shape == y.shape == w.shape == (4, 256)
        assert w.max() <= 1.0 and w.min() >= 0.0
        # at least the response tokens are supervised
        assert w.sum() > 0


def test_instruct_loader_rejects_unknown_dataset():
    with pytest.raises(ValueError):
        InstructLoader(ByteTokenizer(), 2, 8, 0, dataset_name="dolly")
