"""End-to-end CLI tests (reference L0: args.py + main.py).

Runs the real ``main()`` in-process on the 8-device virtual CPU mesh with
``--debug`` tiny models and the offline ByteTokenizer — the reference's
``--debug`` flag served the same integration-fixture role (SURVEY §4).
"""

import json
import os

import numpy as np
import pytest

from building_llm_from_scratch_tpu.args import get_args
from building_llm_from_scratch_tpu.main import main

TEXT = ("Every effort moves you closer to mastery. " * 120)

RECORDS = [
    {"instruction": f"Repeat the word number {i}.", "input": f"word{i}",
     "output": f"word{i} word{i}"}
    for i in range(40)
]


@pytest.fixture()
def data_dir(tmp_path):
    d = tmp_path / "data"
    d.mkdir()
    (d / "corpus.txt").write_text(TEXT)
    (d / "alpaca.json").write_text(json.dumps(RECORDS))
    return str(d)


def _args(data_dir, out_dir, *extra):
    base = [
        "--data_dir", data_dir, "--output_dir", out_dir,
        "--debug", "--byte_tokenizer", "--n_epochs", "1",
        "--batch_size", "8", "--eval_freq", "20",
        "--print_sample_iter", "10000", "--save_ckpt_freq", "10000",
        "--warmup_steps", "2",
    ]
    return get_args(base + list(extra))


def test_cli_pretrain_end_to_end(data_dir, tmp_path):
    out = str(tmp_path / "out")
    trainer = main(_args(data_dir, out))
    assert trainer.global_step > 0
    assert trainer.train_losses and np.isfinite(trainer.train_losses).all()
    # end-of-run observability + export (reference main.py:162-172)
    assert os.path.exists(os.path.join(out, "losses.pdf"))
    assert os.path.exists(os.path.join(out, "model_pg_final.npz"))
    assert os.path.exists(os.path.join(out, "model_pg_final", "manifest.json"))


def test_cli_finetune_lora_end_to_end(data_dir, tmp_path):
    out = str(tmp_path / "out_ft")
    trainer = main(_args(
        data_dir, out, "--finetune", "--dataset", "alpaca",
        "--use_lora", "--lora_rank", "2", "--lora_alpha", "4"))
    assert trainer.use_lora and trainer.global_step > 0
    assert os.path.exists(os.path.join(out, "model_pg_final.npz"))


@pytest.mark.slow
def test_cli_multichip_fsdp(data_dir, tmp_path):
    """--run_type multi_chip shards state over the full 8-device mesh."""
    out = str(tmp_path / "out_mc")
    trainer = main(_args(data_dir, out, "--run_type", "multi_chip",
                         "--shard_mode", "fsdp"))
    wq = trainer.state["trainable"]["blocks"]["attn"]["wq"]
    assert len(wq.sharding.device_set) == 8
    assert np.isfinite(trainer.train_losses).all()


def _run_shardmap_worker(mode, data_dir, tmp_path):
    """Run the sp/pp CLI e2e in a child process (see _cli_shardmap_worker's
    docstring: isolates a rare CPU-collectives interpreter abort and allows
    one retry)."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "_cli_shardmap_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    last = "timed out"
    for attempt in range(3):
        out_dir = str(tmp_path / f"out_{mode}{attempt}")
        try:
            proc = subprocess.run(
                [_sys.executable, worker, mode, data_dir, out_dir],
                capture_output=True, text=True, timeout=900, cwd=repo,
                env=env)
        except subprocess.TimeoutExpired as e:   # hung worker: also retry
            last = f"timeout: {e.stdout}\n{e.stderr}"
            continue
        if proc.returncode == 0 and f"WORKER_{mode.upper()}_OK" in proc.stdout:
            return
        last = f"rc={proc.returncode}: {proc.stdout}\n{proc.stderr}"
    raise AssertionError(f"{mode} CLI worker failed 3 times; last: {last}")


@pytest.mark.slow
def test_cli_multichip_sequence_parallel(data_dir, tmp_path):
    """--sp 2 trains with ring attention over the seq mesh axis."""
    _run_shardmap_worker("sp", data_dir, tmp_path)


def test_checks_sp_accepts_gpt2_dropout(data_dir):
    """Since round 4 the ring schedule supports attention dropout
    (per-shard folded mask PRNG), so GPT-2 + --sp is accepted."""
    args = get_args(["--data_dir", data_dir, "--run_type", "multi_chip",
                     "--sp", "2"])
    assert args.sp == 2 and args.model == "GPT2"


@pytest.mark.slow
def test_cli_multichip_pipeline(data_dir, tmp_path):
    """--shard_mode pp trains with the GPipe schedule (2 stages)."""
    _run_shardmap_worker("pp", data_dir, tmp_path)


@pytest.mark.slow
def test_cli_multichip_pipeline_tensor_parallel(data_dir, tmp_path):
    """--shard_mode pp --tp 2: pipeline stages x Megatron tp from the CLI
    (round-5 VERDICT #6)."""
    _run_shardmap_worker("pp_tp", data_dir, tmp_path)


def test_checks_pp_flag_combinations(data_dir):
    # GPT-2 + pp is ACCEPTED since round 4 (pipeline dropout support)
    args = get_args(["--data_dir", data_dir, "--run_type", "multi_chip",
                     "--shard_mode", "pp", "--batch_size", "8"])
    assert args.model == "GPT2" and args.shard_mode == "pp"
    with pytest.raises(ValueError, match="bf16/fp32 only"):
        get_args(["--data_dir", data_dir, "--run_type", "multi_chip",
                  "--model", "llama3_2", "--num_params", "1B",
                  "--shard_mode", "pp", "--mixed_precision", "bf16_hybrid"])
    with pytest.raises(ValueError, match="divisible"):
        get_args(["--data_dir", data_dir, "--run_type", "multi_chip",
                  "--model", "llama3_2", "--num_params", "1B",
                  "--shard_mode", "pp", "--batch_size", "6"])


def test_cli_resume(data_dir, tmp_path):
    out = str(tmp_path / "out_r")
    first = main(_args(data_dir, out))
    steps_per_run = first.global_step
    resumed = main(_args(data_dir, out, "--resume_from",
                         os.path.join(out, "model_pg_final")))
    assert resumed.global_step == 2 * steps_per_run
    assert resumed.tokens_seen == 2 * first.tokens_seen


@pytest.mark.slow
def test_cli_profile(data_dir, tmp_path):
    out = str(tmp_path / "out_p")
    main(_args(data_dir, out, "--profile", "--profile_steps", "2"))
    profile_dir = os.path.join(out, "profile")
    found = [os.path.join(r, f) for r, _, fs in os.walk(profile_dir)
             for f in fs]
    assert found, "no jax.profiler trace files written"


# ---------------------------------------------------------------------------
# Flag validation (reference args.py:8-35 perform_checks)
# ---------------------------------------------------------------------------

def test_checks_bad_num_params(data_dir):
    with pytest.raises(ValueError, match="Unsupported model configuration"):
        get_args(["--data_dir", data_dir, "--model", "GPT2",
                  "--num_params", "7B"])


def test_checks_missing_data_dir():
    with pytest.raises(FileNotFoundError, match="does not exist"):
        get_args(["--data_dir", "/nonexistent_dir_xyz"])


def test_checks_sharding_needs_multichip(data_dir):
    with pytest.raises(ValueError, match="multi_chip"):
        get_args(["--data_dir", data_dir, "--shard_mode", "fsdp"])


def test_checks_tp_needs_tp_mode(data_dir):
    with pytest.raises(ValueError, match="--shard_mode tp"):
        get_args(["--data_dir", data_dir, "--run_type", "multi_chip",
                  "--tp", "2"])


def test_checks_finetune_dataset_consistency(data_dir):
    with pytest.raises(ValueError, match="alpaca"):
        get_args(["--data_dir", data_dir, "--finetune"])
    with pytest.raises(ValueError, match="finetune"):
        get_args(["--data_dir", data_dir, "--dataset", "alpaca"])


def test_checks_resume_dir_must_exist(data_dir):
    with pytest.raises(FileNotFoundError, match="resume_from"):
        get_args(["--data_dir", data_dir, "--resume_from", "/no/such/ckpt"])


def test_fp16_data_type_never_trains_scalerless(data_dir):
    """Round-2 VERDICT weak #4: --data_type fp16 alone must get the dynamic
    loss scaler; a contradictory policy is rejected at flag-check time."""
    from building_llm_from_scratch_tpu.build_components import build_components

    with pytest.raises(ValueError, match="mixed_precision fp16"):
        get_args(["--data_dir", data_dir, "--data_type", "fp16",
                  "--mixed_precision", "bf16"])

    args = _args(data_dir, "out_unused", "--data_type", "fp16")
    comps = build_components(args)
    assert comps.policy is not None and comps.policy.name == "fp16"
    assert comps.policy.init_loss_scale > 1.0
