"""Fleet observatory tests (serving/transport.py instrumentation,
serving/fleet.py span closure + aggregated /metrics, obs/fleetview.py
merged exporter, scripts/summarize_metrics.py incarnation handling).

The cross-process tracing contract: EVERY submitted request yields
exactly ONE closed span tree on the fleet's JSONL — done, shed,
rejected, expired, or killed mid-decode — carrying request_id + worker
labels and the ``rpc:<method>`` hops as children; worker files join on
the same request id. The aggregated ``/metrics`` endpoint answers from
cached per-worker series (with a staleness gauge) while a worker is
down, in well under a second. The merged exporter is deterministic and
shifts worker rows onto the fleet clock using ``clock_sync`` offsets.
"""

import importlib.util
import json
import os
import re
import signal
import sys
import time

import numpy as np
import pytest

from building_llm_from_scratch_tpu.obs import configure_metrics
from building_llm_from_scratch_tpu.serving import (
    EngineSpec,
    ProcessFleet,
    SamplingParams,
)
from building_llm_from_scratch_tpu.serving.queue import (
    QueueFullError,
    SLOShedError,
)
from building_llm_from_scratch_tpu.serving.transport import (
    RpcClient,
    RpcServer,
    RpcStats,
)

@pytest.fixture
def sink(tmp_path):
    path = tmp_path / "metrics.jsonl"
    logger = configure_metrics(str(path), run_metadata={"test": True})
    yield str(path)
    logger.close()
    configure_metrics(None)


def load_rows(path):
    return [json.loads(line) for line in open(path)]


def fake_spec(**fake_kw):
    fake = dict(n_slots=2, max_queue=32, tpot_s=0.01,
                default_max_new_tokens=8, vocab_size=96)
    fake.update(fake_kw)
    return EngineSpec(fake=fake)


def make_fleet(n=2, tmp_path=None, spec=None, **kw):
    kw.setdefault("heartbeat_s", 0.1)
    kw.setdefault("heartbeat_timeout_s", 5.0)
    kw.setdefault("max_restarts", 2)
    kw.setdefault("restart_backoff_s", 0.2)
    kw.setdefault("ready_timeout_s", 120.0)
    if tmp_path is not None:
        kw.setdefault("socket_dir", str(tmp_path / "socks"))
        os.makedirs(kw["socket_dir"], exist_ok=True)
    return ProcessFleet(spec or fake_spec(), n, **kw)


def wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def load_summarize_metrics():
    """scripts/ is not a package: load the renderer by file path (the
    same jax-free loading discipline the script itself uses)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "scripts", "summarize_metrics.py")
    spec = importlib.util.spec_from_file_location("_summarize_metrics",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_summarize_metrics"] = mod
    spec.loader.exec_module(mod)
    return mod


# -- raw transport instrumentation ---------------------------------------


def test_rpc_stats_latency_seconds_and_frame_bytes(tmp_path):
    """Per-method client/server histograms count in SECONDS and the
    frame-byte counters match real frame traffic; every reply carries
    the ``srv`` clock stamp that feeds the client's offset sample."""
    path = str(tmp_path / "rpc.sock")
    server_stats = RpcStats()
    seen_traces = []

    def handler(method, args, sock):
        if method == "boom":
            raise ValueError("no")
        time.sleep(0.01)
        return {"echo": args.get("x")}

    srv = RpcServer(path, handler, stats=server_stats,
                    span_hook=lambda m, tr, t0, dur, ok:
                        seen_traces.append((m, tr, ok)))
    srv.start()
    cli_stats = RpcStats()
    cli = RpcClient(path, timeout=5.0, stats=cli_stats)
    timings = []
    try:
        for i in range(3):
            out = cli.call("echo", x=i, trace_ctx={"request_id": 42},
                           on_timing=timings.append)
            assert out == {"echo": i}
        with pytest.raises(ValueError):
            cli.call("boom")
    finally:
        cli.close()
        srv.stop()

    for stats, side in ((cli_stats, "client"), (server_stats, "server")):
        snap = stats.snapshot()
        e = snap["echo"]
        assert e["calls"] == 3 and e["errors"] == 0, side
        lat = e["latency"]
        # seconds units: 3 calls of a 10ms handler sum to [0.03, 3.0)
        # — a ms-unit regression would put the sum at 30+
        assert 0.03 <= lat["sum"] < 3.0, (side, lat)
        assert lat["count"] == 3
        assert snap["boom"]["errors"] == 1
    ce = cli_stats.snapshot()["echo"]
    assert ce["bytes_sent"] > 0 and ce["bytes_received"] > 0
    # the server received exactly what the client sent
    assert server_stats.snapshot()["echo"]["bytes_received"] == \
        ce["bytes_sent"]
    # timing hook: one dict per call with the rpc-child-span fields
    assert len(timings) == 3
    for t in timings:
        assert t["method"] == "echo"
        assert 0.0 < t["dur_s"] < 3.0
        assert t["bytes_sent"] > 0
    # trace context reached the server's span hook, errors flagged
    assert [m for m, _, _ in seen_traces] == ["echo"] * 3
    assert all(tr == {"request_id": 42} and ok
               for _, tr, ok in seen_traces)
    # clock sample: NTP midpoint on a local socket is sub-second tight
    clock = cli.clock
    assert clock is not None and clock.rtt_s > 0.0
    assert abs(clock.offset_s) < 1.0
    assert clock.uncertainty_s == pytest.approx(clock.rtt_s / 2.0)


# -- the cross-process span audit ----------------------------------------


@pytest.mark.slow
def test_span_audit_one_closed_tree_per_outcome(tmp_path, sink):
    """One request per outcome — done, shed (tight deadline), rejected
    (queue full), expired (deadline passed while queued), worker_dead
    (kill -9 mid-decode) — through a 2-worker fleet: the fleet JSONL
    holds exactly ONE closed ``request`` span per request id, labeled
    with request_id/worker/incarnation and carrying ``rpc:`` children;
    worker files join on the same ids, and the victim's file stacks one
    header per incarnation (the run_stats regression)."""
    spec = fake_spec(n_slots=1, max_queue=2, tpot_s=0.05)
    fleet = make_fleet(2, tmp_path, spec=spec, metrics_base=sink,
                       max_restarts=1).start()
    try:
        # outcome: done
        h_done = fleet.submit(np.array([3], np.int32),
                              SamplingParams(max_new_tokens=4),
                              block=True, timeout=10.0)
        h_done.result(timeout=30.0)
        # outcome: shed — deadline below the engine's own decode
        # estimate, refused by every worker at submit
        with pytest.raises(SLOShedError):
            fleet.submit(np.array([5], np.int32),
                         SamplingParams(max_new_tokens=8,
                                        deadline_s=0.01))
        # saturate both single-slot workers with long decodes
        blockers = [fleet.submit(np.array([10 + i], np.int32),
                                 SamplingParams(max_new_tokens=60),
                                 block=True, timeout=10.0)
                    for i in range(2)]
        time.sleep(0.2)
        # outcome: expired — passes the shed estimate but its deadline
        # lapses while queued behind a blocker
        h_exp = fleet.submit(np.array([20], np.int32),
                             SamplingParams(max_new_tokens=2,
                                            deadline_s=0.2))
        # outcome: rejected — fill every queue slot until a submit is
        # refused by both workers
        fillers, rejected = [], False
        for i in range(8):
            try:
                fillers.append(fleet.submit(
                    np.array([30 + i], np.int32),
                    SamplingParams(max_new_tokens=2)))
            except QueueFullError:
                rejected = True
                break
        assert rejected, "queues never filled"
        for h in blockers + fillers:
            h.result(timeout=30.0)
        with pytest.raises(Exception, match="expired"):
            h_exp.result(timeout=30.0)
        # outcome: worker_dead — kill the serving worker mid-decode
        h_dead = fleet.submit(np.array([50], np.int32),
                              SamplingParams(max_new_tokens=60),
                              block=True, timeout=10.0)
        time.sleep(0.2)
        victim = h_dead.route["replica"]
        os.kill(fleet.workers[victim].pid, signal.SIGKILL)
        with pytest.raises(RuntimeError, match="worker_dead"):
            h_dead.result(timeout=60.0)
        wait_for(lambda: fleet.stats()["worker_restarts"] == 1, 30.0,
                 "the victim to restart (second incarnation)")
    finally:
        fleet.shutdown(drain=False)

    rows = load_rows(sink)
    events = [r for r in rows if r.get("type") == "event"]
    shed_ids = [e["request_id"] for e in events
                if e["event"] == "request_shed"]
    rej_ids = [e["request_id"] for e in events
               if e["event"] == "request_rejected"]
    assert len(shed_ids) == 1 and len(rej_ids) == 1
    expect = {h_done.id: "length", shed_ids[0]: "shed",
              rej_ids[0]: "rejected", h_exp.id: "expired",
              h_dead.id: "error"}
    for h in blockers + fillers:
        expect[h.id] = "length"

    spans = [r for r in rows if r.get("type") == "span"
             and r.get("name") == "request"]
    by_id = {}
    for s in spans:
        assert s["request_id"] not in by_id, (
            f"request {s['request_id']} emitted more than one tree")
        by_id[s["request_id"]] = s
    assert set(by_id) == set(expect), "a submitted request left no tree"
    for rid, outcome in expect.items():
        s = by_id[rid]
        assert s["outcome"] == outcome, (rid, s)
        assert isinstance(s["worker"], int) and s["worker"] >= 0
        assert isinstance(s["incarnation"], int)
        assert s["dur_s"] >= 0.0
        kids = s.get("children") or []
        assert any(c["name"].startswith("rpc:") for c in kids), (
            f"request {rid} ({outcome}) has no rpc child spans")
        for c in kids:   # closed tree: children inside the root
            assert c["t0"] >= s["t0"]
            assert c["t0"] + c["dur_s"] <= s["t0"] + s["dur_s"] + 1e-6
    assert by_id[h_dead.id]["worker"] == victim

    # worker files join on the same fleet request ids
    worker_spans = {}
    for i in range(2):
        wrows = load_rows(f"{sink}.worker{i}.jsonl")
        for r in wrows:
            if r.get("type") == "span" and r.get("name") == \
                    "worker_request":
                worker_spans.setdefault(r["request_id"], []).append(r)
    assert set(worker_spans) <= set(expect)
    for rid in [h_done.id] + [h.id for h in blockers + fillers]:
        assert len(worker_spans[rid]) == 1, (
            f"completed request {rid} must have exactly one worker span")
        assert worker_spans[rid][0].get("replica") is not None
        assert worker_spans[rid][0].get("pid") is not None

    # clock_sync samples cover the victim's BOTH incarnations
    sync = [e for e in events if e["event"] == "clock_sync"]
    assert sync, "no clock_sync events on the fleet file"
    for e in sync:
        assert isinstance(e["offset_s"], (int, float))
        assert e["uncertainty_s"] >= 0.0
        assert abs(e["offset_s"]) < 1.0       # same host: tiny skew
    assert {(e["replica"], e.get("incarnation")) for e in sync} >= {
        (victim, 0), (victim, 1)}

    # the victim's file stacks one header per incarnation, and the
    # renderer's run_stats splits + labels them (the regression the
    # append-mode files used to break)
    victim_file = f"{sink}.worker{victim}.jsonl"
    headers = [r for r in load_rows(victim_file)
               if r.get("type") == "header"]
    assert [(h["replica"], h["incarnation"]) for h in headers] == [
        (victim, 0), (victim, 1)]
    sm = load_summarize_metrics()
    stats = sm.run_stats(victim_file)
    assert stats["n_incarnations"] == 2
    assert set(stats["incarnations"]) == {
        f"replica{victim}.inc0", f"replica{victim}.inc1"}
    assert len(sm.load_segments(victim_file)) == 2

    # merged exporter over the real artifacts: every request tree
    # survives the merge and the death/restart incidents are visible
    from building_llm_from_scratch_tpu.obs.fleetview import (
        export_fleet_trace,
    )
    out = str(tmp_path / "fleet_trace.json")
    meta = export_fleet_trace(sink, out)
    assert meta["n_request_spans"] == len(expect)
    assert meta["n_incarnations"] == 3       # 2 workers + 1 restart
    assert meta["n_flow_edges"] >= 1
    trace = json.load(open(out))
    instants = [e for e in trace["traceEvents"] if e.get("ph") == "i"]
    assert any(e["name"] == "worker_dead" for e in instants)
    assert any(e["name"] == "worker_restart" for e in instants)


# -- aggregated /metrics under outage ------------------------------------


@pytest.mark.slow
def test_aggregated_metrics_cached_and_stale_during_outage(tmp_path,
                                                           sink):
    fleet = make_fleet(2, tmp_path, metrics_base=sink,
                       max_restarts=0).start()
    try:
        # heartbeats carry PAIRED (wall, monotonic) stamps; the control
        # channel holds a live NTP-style clock sample per worker
        w0 = fleet.workers[0]
        wait_for(lambda: w0.last_beat_wall is not None, 10.0,
                 "a paired-timestamp heartbeat")
        assert abs(time.time() - w0.last_beat_wall) < 5.0
        assert w0.ctrl.clock is not None
        assert w0.ctrl.clock.rtt_s > 0.0
        assert abs(w0.ctrl.clock.offset_s) < 1.0

        h = fleet.submit(np.array([7], np.int32),
                         SamplingParams(max_new_tokens=4), block=True,
                         timeout=10.0)
        h.result(timeout=30.0)
        text = fleet.prometheus_text()       # also primes the cache
        assert re.search(r'fleet_workers_up 2(\.0)?\b', text)
        for i in (0, 1):
            assert re.search(
                r'fleet_worker_metrics_stale\{worker="%d",'
                r'incarnation="0"\} 0(\.0)?\b' % i, text), text
        # per-worker label passthrough on the workers' own series
        assert re.search(r'serve_requests_finished[^\n]*worker="0"',
                         text)
        assert re.search(r'worker="1"', text)
        # the fleet's per-method rpc instrumentation
        assert re.search(
            r'fleet_rpc_client_calls_total\{method="ping"\} [1-9]',
            text)
        assert 'fleet_rpc_client_latency_seconds' in text
        assert re.search(
            r'fleet_rpc_client_frame_bytes_sent_total\{method="submit"\}'
            r' [1-9]', text)

        os.kill(fleet.workers[0].pid, signal.SIGKILL)
        wait_for(lambda: fleet.stats()["worker_deaths"] == 1, 10.0,
                 "the death to be detected")
        time.sleep(1.0)                      # age past the staleness bar
        t0 = time.monotonic()
        text = fleet.prometheus_text()
        dt = time.monotonic() - t0
        assert dt < 1.0, f"/metrics blocked {dt:.2f}s during outage"
        assert re.search(r'fleet_workers_up 1(\.0)?\b', text)
        # the dead worker's cached series are still served, marked stale
        assert re.search(
            r'fleet_worker_metrics_stale\{worker="0",incarnation="0"\} '
            r'1(\.0)?\b', text), text
        assert re.search(
            r'fleet_worker_metrics_stale\{worker="1",incarnation="0"\} '
            r'0(\.0)?\b', text)
        assert re.search(r'serve_requests_finished[^\n]*worker="0"',
                         text)
        assert re.search(r'fleet_worker_deaths_total 1\b', text)
    finally:
        fleet.shutdown(drain=False)

    # the flight recorder snapshotted its ring on death + budget
    # exhaustion, and said so on the fleet's JSONL
    snaps = sorted(
        p for p in os.listdir(os.path.dirname(sink))
        if re.match(r"metrics\.jsonl\.incident\d+\.json$", p))
    assert snaps, "no incident snapshot written"
    payload = json.load(open(os.path.join(os.path.dirname(sink),
                                          snaps[0])))
    assert payload["reason"].startswith("worker_dead")
    assert payload["n_events"] >= 1
    kinds = [e["kind"] for e in payload["events"]]
    assert "worker_spawn" in kinds and "worker_dead" in kinds
    ev = [r for r in load_rows(sink) if r.get("type") == "event"
          and r.get("event") == "incident_snapshot"]
    assert ev and ev[0]["reason"].startswith("worker_dead")
    assert os.path.basename(ev[0]["path"]) == snaps[0]


# -- exporter determinism + skew correction on fixtures ------------------


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_fleet_exporter_deterministic_and_skew_corrected(tmp_path):
    """Fixture fleet+worker files with a KNOWN 0.5s clock skew: the
    exporter lands the worker span at the fleet-clock instant, keeps
    every incarnation, and two exports are byte-identical."""
    fleet_jsonl = str(tmp_path / "m.jsonl")
    _write_jsonl(fleet_jsonl, [
        {"type": "header", "time": 1000.0, "schema_version": 10},
        {"type": "event", "time": 1000.1, "event": "clock_sync",
         "replica": 0, "incarnation": 0, "offset_s": 0.5,
         "uncertainty_s": 0.001, "rtt_s": 0.002, "n_samples": 3},
        {"type": "span", "time": 1001.0, "name": "request",
         "cat": "request", "t0": 1000.2, "dur_s": 0.5,
         "children": [{"name": "rpc:submit", "t0": 1000.2,
                       "dur_s": 0.01}],
         "request_id": 7, "outcome": "length", "worker": 0,
         "incarnation": 0},
        {"type": "event", "time": 1000.9, "event": "worker_dead",
         "replica": 0, "reason": "test"},
    ])
    worker_jsonl = fleet_jsonl + ".worker0.jsonl"
    _write_jsonl(worker_jsonl, [
        {"type": "header", "time": 1000.6, "schema_version": 10,
         "replica": 0, "incarnation": 0, "pid": 111,
         "role": "fleet_worker"},
        # worker clock runs 0.5s AHEAD: uncorrected, this span would
        # render 0.5s after the rpc that delivered it
        {"type": "span", "time": 1000.8, "name": "worker_request",
         "cat": "request", "t0": 1000.7, "dur_s": 0.4,
         "request_id": 7, "local_request_id": 1, "replica": 0,
         "outcome": "length"},
        {"type": "header", "time": 1002.0, "schema_version": 10,
         "replica": 0, "incarnation": 1, "pid": 222,
         "role": "fleet_worker"},
        {"type": "span", "time": 1002.5, "name": "worker_request",
         "cat": "request", "t0": 1002.4, "dur_s": 0.1,
         "request_id": 9, "local_request_id": 1, "replica": 0,
         "outcome": "length"},
    ])

    from building_llm_from_scratch_tpu.obs.fleetview import (
        export_fleet_trace,
    )
    out_a = str(tmp_path / "a.json")
    out_b = str(tmp_path / "b.json")
    meta = export_fleet_trace(fleet_jsonl, out_a)
    export_fleet_trace(fleet_jsonl, out_b)
    assert open(out_a, "rb").read() == open(out_b, "rb").read(), (
        "exporter output must be deterministic")

    assert meta["n_request_spans"] == 1
    assert meta["n_worker_files"] == 1
    assert meta["n_incarnations"] == 2
    assert meta["n_worker_spans"] == 2
    assert meta["n_flow_edges"] == 1
    off = meta["clock_offsets_s"]["worker0.inc0"]
    assert off["offset_s"] == pytest.approx(0.5)
    assert off["uncertainty_s"] == pytest.approx(0.001)
    # inc1 never got its own sample: it inherits the replica's best
    assert meta["clock_offsets_s"]["worker0.inc1"]["offset_s"] == \
        pytest.approx(0.5)

    trace = json.load(open(out_a))
    slices = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    fleet_span = next(e for e in slices if e["name"] == "request")
    worker_span = next(e for e in slices
                       if e["name"] == "worker_request"
                       and e["args"].get("request_id") == 7)
    # skew-corrected: 1000.7 − 0.5 == the fleet span's own t0
    assert worker_span["ts"] == pytest.approx(fleet_span["ts"], abs=1.0)
    flows = [e for e in trace["traceEvents"]
             if e.get("ph") in ("s", "f")]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert len({e["id"] for e in flows}) == 1
