"""Two-process jax.distributed smoke test (round-2 VERDICT weak #7).

Spawns two real CPU processes (4 virtual devices each) that form one
8-device mesh, run 3 fsdp train steps on process-local batches, gather the
full state on every host, and round-trip a checkpoint — first coverage of
the code paths single-process tests cannot execute.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(tmp_path, nproc: int, mode: str, timeout: int = 240):
    from conftest import distributed_spawn_lock

    ckdir = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # worker sets its own device count
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    with distributed_spawn_lock():
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, WORKER, str(pid), str(nproc), str(port),
                 ckdir, mode],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=REPO, env=env)
            for pid in range(nproc)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("distributed workers timed out:\n" + "\n".join(
                p.communicate()[0] or "" for p in procs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_{pid}_OK" in out, out


@pytest.mark.slow
def test_two_process_fsdp_train_and_checkpoint(tmp_path):
    """2 hosts x 4 devices, fsdp: train, sharded save, streamed restore,
    resume step."""
    _run_workers(tmp_path, nproc=2, mode="fsdp")


@pytest.mark.slow
def test_two_process_pipeline_parallel(tmp_path):
    """2 hosts x 4 devices, pp: stage axis over hosts, per-process
    microbatch feeds, 3 finite pipelined train steps (round-5 VERDICT
    #5 — pipeline parallelism leaves one host)."""
    _run_workers(tmp_path, nproc=2, mode="pp")


@pytest.mark.slow
def test_four_process_zero1_resume(tmp_path):
    """4 hosts x 4 devices (16-device mesh), zero1 optimizer-state
    sharding: train, sharded save, restore, resume (round-3 VERDICT
    weakness #5 — zero1 had never executed across real processes)."""
    _run_workers(tmp_path, nproc=4, mode="zero1", timeout=360)
