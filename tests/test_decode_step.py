"""Fused decode-step kernel (ops/decode_step.py) parity vs the jnp
decode path, plus the custom-VJP norm gradient checks (round 5).

The pallas kernel tests need the real chip (RUN_TPU_TESTS=1); the norm
gradient tests run everywhere.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

needs_tpu = pytest.mark.skipif(jax.default_backend() != "tpu",
                               reason="pallas TPU kernel (RUN_TPU_TESTS=1)")


@needs_tpu
@pytest.mark.parametrize("B,Hq,Hkv,hd,Tmax,t", [
    (2, 12, 12, 64, 320, 5),      # GPT2-ish MHA
    (2, 32, 8, 64, 320, 17),      # GQA
    (8, 12, 12, 64, 320, 0),      # append at the very start
    (1, 32, 8, 128, 256, 100),    # large head dim
])
def test_fused_decode_step_matches_jnp_path(B, Hq, Hkv, hd, Tmax, t):
    from building_llm_from_scratch_tpu.ops.attention import decode_attention
    from building_llm_from_scratch_tpu.ops.decode_step import (
        fused_decode_step,
    )

    Tq = 1
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    q = jax.random.normal(ks[0], (B, Tq, Hq, hd), jnp.bfloat16)
    kn = jax.random.normal(ks[1], (B, Tq, Hkv, hd), jnp.bfloat16)
    vn = jax.random.normal(ks[2], (B, Tq, Hkv, hd), jnp.bfloat16)
    K = jax.random.normal(ks[3], (B, Hkv, Tmax, hd), jnp.bfloat16)
    V = jax.random.normal(ks[4], (B, Hkv, Tmax, hd), jnp.bfloat16)
    length = jnp.asarray(t, jnp.int32)
    positions = t + jnp.arange(Tq)

    K2 = jax.lax.dynamic_update_slice(K, kn.transpose(0, 2, 1, 3),
                                      (0, 0, t, 0))
    V2 = jax.lax.dynamic_update_slice(V, vn.transpose(0, 2, 1, 3),
                                      (0, 0, t, 0))
    ref = decode_attention(q, K2, V2, q_positions=positions,
                           kv_length=length + Tq)

    out, Ko, Vo = jax.jit(fused_decode_step)(q, kn, vn, K, V, length)
    np.testing.assert_allclose(np.asarray(Ko, np.float32),
                               np.asarray(K2, np.float32))
    np.testing.assert_allclose(np.asarray(Vo, np.float32),
                               np.asarray(V2, np.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=2e-2, rtol=2e-2)


@needs_tpu
def test_fused_decode_step_per_row_lengths():
    """Per-row lengths (the serving engine's slot batch, ops/decode_step
    slot semantics): each row appends at ITS offset and attends its own
    valid prefix — must match running each row alone at a scalar length."""
    from building_llm_from_scratch_tpu.ops.decode_step import (
        fused_decode_step,
    )

    B, Hq, Hkv, hd, Tmax = 3, 12, 12, 64, 320
    ks = jax.random.split(jax.random.PRNGKey(7), 5)
    q = jax.random.normal(ks[0], (B, 1, Hq, hd), jnp.bfloat16)
    kn = jax.random.normal(ks[1], (B, 1, Hkv, hd), jnp.bfloat16)
    vn = jax.random.normal(ks[2], (B, 1, Hkv, hd), jnp.bfloat16)
    K = jax.random.normal(ks[3], (B, Hkv, Tmax, hd), jnp.bfloat16)
    V = jax.random.normal(ks[4], (B, Hkv, Tmax, hd), jnp.bfloat16)
    lengths = jnp.asarray([0, 7, 133], jnp.int32)

    out, Ko, Vo = jax.jit(fused_decode_step)(q, kn, vn, K, V, lengths)
    for b in range(B):
        ob, Kb, Vb = jax.jit(fused_decode_step)(
            q[b:b + 1], kn[b:b + 1], vn[b:b + 1], K[b:b + 1], V[b:b + 1],
            lengths[b])
        np.testing.assert_allclose(np.asarray(Ko[b:b + 1], np.float32),
                                   np.asarray(Kb, np.float32))
        np.testing.assert_allclose(np.asarray(Vo[b:b + 1], np.float32),
                                   np.asarray(Vb, np.float32))
        np.testing.assert_allclose(np.asarray(out[b:b + 1], np.float32),
                                   np.asarray(ob, np.float32),
                                   atol=2e-2, rtol=2e-2)


def test_decode_step_supports_shape_gates():
    from building_llm_from_scratch_tpu.ops.decode_step import supports_shape

    assert supports_shape(1, 320, 64)
    assert not supports_shape(2, 320, 64)      # single-token only
    assert not supports_shape(1, 60, 64)       # Tmax must be 8-aligned
    assert not supports_shape(1, 320, 96)      # head dim lane alignment


# ---------------------------------------------------------------------------
# custom-VJP norms: gradients == autodiff of the plain formulation
# ---------------------------------------------------------------------------

def _ref_layernorm(x, s, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    m = jnp.mean(x32, -1, keepdims=True)
    v = jnp.var(x32, -1, keepdims=True)
    y = (x32 - m) / jnp.sqrt(v + eps) * s.astype(jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def _ref_rmsnorm(x, s, eps=1e-5):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), -1, keepdims=True)
    return (x32 / jnp.sqrt(ms + eps) * s.astype(jnp.float32)).astype(x.dtype)


@pytest.fixture()
def _norm_inputs():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 7, 64)) * 2 + 0.3
    s = jax.random.normal(jax.random.PRNGKey(1), (64,)) * 0.5 + 1.0
    b = jax.random.normal(jax.random.PRNGKey(2), (64,)) * 0.1
    return x, s, b


def test_layernorm_custom_vjp_gradients(_norm_inputs):
    from building_llm_from_scratch_tpu.ops.norms import layernorm

    x, s, b = _norm_inputs
    np.testing.assert_allclose(layernorm(x, s, b), _ref_layernorm(x, s, b),
                               rtol=1e-6, atol=1e-6)
    g1 = jax.grad(lambda *a: jnp.sum(jnp.sin(layernorm(*a))), (0, 1, 2))(
        x, s, b)
    g2 = jax.grad(lambda *a: jnp.sum(jnp.sin(_ref_layernorm(*a))), (0, 1, 2))(
        x, s, b)
    for a, r in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-5, atol=2e-6)


def test_layernorm_custom_vjp_gradients_no_bias(_norm_inputs):
    from building_llm_from_scratch_tpu.ops.norms import layernorm

    x, s, _ = _norm_inputs
    g1 = jax.grad(lambda x, s: jnp.sum(jnp.sin(layernorm(x, s, None))),
                  (0, 1))(x, s)
    g2 = jax.grad(lambda x, s: jnp.sum(jnp.sin(_ref_layernorm(x, s, None))),
                  (0, 1))(x, s)
    for a, r in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-5, atol=2e-6)


def test_rmsnorm_custom_vjp_gradients(_norm_inputs):
    from building_llm_from_scratch_tpu.ops.norms import rmsnorm

    x, s, _ = _norm_inputs
    np.testing.assert_allclose(rmsnorm(x, s), _ref_rmsnorm(x, s),
                               rtol=1e-6, atol=1e-6)
    g1 = jax.grad(lambda x, s: jnp.sum(jnp.sin(rmsnorm(x, s))), (0, 1))(x, s)
    g2 = jax.grad(lambda x, s: jnp.sum(jnp.sin(_ref_rmsnorm(x, s))),
                  (0, 1))(x, s)
    for a, r in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-5, atol=2e-6)


@needs_tpu
@pytest.mark.parametrize("N,D,V", [(1024, 768, 50257), (256, 128, 999)])
def test_pallas_xent_fwd_matches_xla(N, D, V, monkeypatch):
    """ops/xent_fwd_pallas.py (opt-in BLLM_XENT_PALLAS=1): nll and lse
    match the XLA online-logsumexp forward exactly. The reference call
    must NOT itself route through the kernel (it would if the opt-in env
    var were exported in this process — the comparison would be
    vacuous), so the gate is forced off for it."""
    from building_llm_from_scratch_tpu.ops.softmax_xent import (
        _xent_fwd_impl,
    )
    from building_llm_from_scratch_tpu.ops.xent_fwd_pallas import xent_fwd

    monkeypatch.setenv("BLLM_XENT_PALLAS", "0")
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(ks[0], (N, D), jnp.bfloat16)
    w = jax.random.normal(ks[1], (D, V), jnp.bfloat16) * 0.02
    t = jax.random.randint(ks[2], (N,), 0, V)
    nll, lse = jax.jit(xent_fwd)(x, w, t)
    nll_ref, lse_ref = _xent_fwd_impl(x, w, t, 51200)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(nll), np.asarray(nll_ref),
                               rtol=1e-4, atol=2e-4)


def test_pallas_xent_supports_shape_gates():
    from building_llm_from_scratch_tpu.ops.xent_fwd_pallas import (
        supports_shape,
    )

    assert supports_shape(8192, 768, 50257)
    assert not supports_shape(100, 768, 50257)     # row misalignment
    assert not supports_shape(65536, 4096, 128256)  # VMEM blowout


def test_fused_dropout_degenerate_rows_fall_back():
    """ADVICE r4 low #3: prime leading dims (best row block < 8) must not
    take the pallas path."""
    from building_llm_from_scratch_tpu.ops.fused_dropout import (
        supports_shape,
    )

    assert supports_shape((8, 1024, 768))
    assert not supports_shape((997, 128))     # prime rows -> r degenerates
    assert not supports_shape((1, 3, 128))    # tiny fold
    assert not supports_shape((8, 100))       # lane misalignment