"""Pretrained-weight loading tests.

Golden-logit parity (SURVEY.md §4 "golden-logit parity vs HF checkpoints"):
random-initialized ``transformers`` models built OFFLINE from configs serve
as the oracle — their state dicts have the exact HF naming/fusing the real
checkpoints use, and their torch forward gives reference logits. Loading
those state dicts through our converters must reproduce the logits.

Also covers: the Meta-naming (w2/w3 swap) map, the weight-tying fallback,
shard-aware device_put, and the torch-free .pth / safetensors readers
against files written by torch itself.
"""

import numpy as np
import pytest

import jax

from building_llm_from_scratch_tpu.configs import ModelConfig
from building_llm_from_scratch_tpu.models import forward
from building_llm_from_scratch_tpu.weights import (
    convert_gpt2_state_dict,
    convert_llama_hf_state_dict,
    convert_llama_meta_state_dict,
    load_state_dict_file,
)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _np_sd(model) -> dict:
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}


# ---------------------------------------------------------------------------
# GPT-2 golden logits
# ---------------------------------------------------------------------------

GPT2_TINY = ModelConfig(
    name="gpt2-tiny", vocab_size=96, context_length=32, emb_dim=32,
    n_heads=2, n_layers=3, hidden_dim=128, n_kv_groups=2,
    norm="layernorm", positional="learned", activation="gelu",
    qkv_bias=True, attn_out_bias=True, mlp_bias=True, norm_bias=True,
    drop_rate=0.0, dtype="fp32")


@pytest.fixture(scope="module")
def gpt2_oracle():
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=3, n_head=2,
        activation_function="gelu",           # exact-erf, matching ops.gelu
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        layer_norm_epsilon=1e-5))
    hf.eval()
    return hf


def test_gpt2_golden_logits(gpt2_oracle):
    """Fused-QKV split + Conv1D layout + tied head reproduce HF logits.

    The reference's GPT-2 loader is broken (VERDICT §2.3 #3 — wrong attr
    names), so torch-HF itself is the oracle, not the reference mapping.
    """
    params = convert_gpt2_state_dict(_np_sd(gpt2_oracle), GPT2_TINY)
    x = np.array([[1, 5, 9, 2, 44, 91, 3, 17]], np.int32)
    with torch.no_grad():
        want = gpt2_oracle(torch.tensor(x, dtype=torch.long)).logits.numpy()
    got = np.asarray(forward(params, GPT2_TINY, x))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_gpt2_requires_qkv_bias_config():
    with pytest.raises(ValueError, match="qkv_bias=True"):
        convert_gpt2_state_dict({}, GPT2_TINY.replace(qkv_bias=False))


def test_gpt2_shape_mismatch_raises(gpt2_oracle):
    sd = _np_sd(gpt2_oracle)
    sd["transformer.h.0.attn.c_attn.weight"] = np.zeros((8, 24), np.float32)
    with pytest.raises(ValueError, match="Shape mismatch"):
        convert_gpt2_state_dict(sd, GPT2_TINY)


# ---------------------------------------------------------------------------
# LLaMA golden logits (GQA + RoPE + SwiGLU + RMSNorm)
# ---------------------------------------------------------------------------

LLAMA_TINY = ModelConfig(
    name="llama-tiny", vocab_size=96, context_length=64, emb_dim=32,
    n_heads=4, n_layers=3, hidden_dim=64, n_kv_groups=2,
    norm="rmsnorm", positional="rope", activation="swiglu",
    rope_base=10_000.0, rmsnorm_eps=1e-5, drop_rate=0.0, dtype="fp32",
    eos_id=2, eos_text="</s>")


@pytest.fixture(scope="module")
def llama_oracle():
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10_000.0,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        attention_dropout=0.0))
    hf.eval()
    return hf


def test_llama_hf_golden_logits(llama_oracle):
    params = convert_llama_hf_state_dict(_np_sd(llama_oracle), LLAMA_TINY)
    x = np.array([[3, 11, 7, 2, 64, 95, 0, 33, 12, 8]], np.int32)
    with torch.no_grad():
        want = llama_oracle(torch.tensor(x, dtype=torch.long)).logits.numpy()
    got = np.asarray(forward(params, LLAMA_TINY, x))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_llama_weight_tying_fallback(llama_oracle):
    """No lm_head.weight -> head ties to the embedding
    (reference load_weights_llama3.py:81-85)."""
    sd = _np_sd(llama_oracle)
    del sd["lm_head.weight"]
    params = convert_llama_hf_state_dict(sd, LLAMA_TINY)
    np.testing.assert_array_equal(
        np.asarray(params["head"]["weight"]),
        sd["model.embed_tokens.weight"].T)


def _to_meta_naming(hf_sd: dict, n_layers: int) -> dict:
    """Rename an HF llama state dict into Meta's consolidated naming,
    including Meta's w1=gate / w3=up / w2=down layout that produces the
    reference's 'swap' (load_weights_llama2.py:55-63)."""
    meta = {
        "tok_embeddings.weight": hf_sd["model.embed_tokens.weight"],
        "norm.weight": hf_sd["model.norm.weight"],
        "output.weight": hf_sd["lm_head.weight"],
    }
    for l in range(n_layers):
        h = f"model.layers.{l}"
        m = f"layers.{l}"
        meta[f"{m}.attention.wq.weight"] = hf_sd[f"{h}.self_attn.q_proj.weight"]
        meta[f"{m}.attention.wk.weight"] = hf_sd[f"{h}.self_attn.k_proj.weight"]
        meta[f"{m}.attention.wv.weight"] = hf_sd[f"{h}.self_attn.v_proj.weight"]
        meta[f"{m}.attention.wo.weight"] = hf_sd[f"{h}.self_attn.o_proj.weight"]
        meta[f"{m}.feed_forward.w1.weight"] = hf_sd[f"{h}.mlp.gate_proj.weight"]
        meta[f"{m}.feed_forward.w3.weight"] = hf_sd[f"{h}.mlp.up_proj.weight"]
        meta[f"{m}.feed_forward.w2.weight"] = hf_sd[f"{h}.mlp.down_proj.weight"]
        meta[f"{m}.attention_norm.weight"] = hf_sd[f"{h}.input_layernorm.weight"]
        meta[f"{m}.ffn_norm.weight"] = hf_sd[f"{h}.post_attention_layernorm.weight"]
    return meta


def test_llama_meta_naming_matches_hf_naming(llama_oracle):
    """The Meta-format converter (w2/w3 swap) and the HF-format converter
    must produce identical param trees from equivalent checkpoints."""
    hf_sd = _np_sd(llama_oracle)
    from_hf = convert_llama_hf_state_dict(hf_sd, LLAMA_TINY)
    from_meta = convert_llama_meta_state_dict(
        _to_meta_naming(hf_sd, LLAMA_TINY.n_layers), LLAMA_TINY)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_flatten_with_path(from_hf)[0],
            jax.tree_util.tree_flatten_with_path(from_meta)[0]):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Shard-aware load
# ---------------------------------------------------------------------------

def test_load_directly_onto_fsdp_sharding(llama_oracle):
    """Leaves land on the mesh sharding at load time (SURVEY §7: 8B weights
    must never materialize unsharded) with unchanged values."""
    from building_llm_from_scratch_tpu.parallel import build_mesh_plan

    plan = build_mesh_plan("fsdp")
    sd = _np_sd(llama_oracle)
    sharded = convert_llama_hf_state_dict(sd, LLAMA_TINY, plan=plan)
    plain = convert_llama_hf_state_dict(sd, LLAMA_TINY)

    gate = sharded["blocks"]["mlp"]["gate"]            # (L, 32, 64): 64 % 8 == 0
    assert len(gate.sharding.device_set) == 8
    for a, b in zip(jax.tree_util.tree_leaves(sharded),
                    jax.tree_util.tree_leaves(plain)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Torch-free file readers vs torch-written files
# ---------------------------------------------------------------------------

def test_torch_pth_reader_roundtrip(tmp_path):
    torch.manual_seed(1)
    sd = {
        "a.weight": torch.randn(5, 3),
        "b.weight": torch.randn(7).to(torch.bfloat16),
        "c.ids": torch.arange(6, dtype=torch.int64).reshape(2, 3),
    }
    p = tmp_path / "ckpt.pth"
    torch.save(sd, p)
    got = load_state_dict_file(str(p))
    assert set(got) == set(sd)
    np.testing.assert_allclose(got["a.weight"], sd["a.weight"].numpy())
    np.testing.assert_allclose(got["b.weight"].astype(np.float32),
                               sd["b.weight"].float().numpy())
    np.testing.assert_array_equal(got["c.ids"], sd["c.ids"].numpy())


def test_safetensors_reader_roundtrip(tmp_path):
    from safetensors.torch import save_file

    torch.manual_seed(2)
    sd = {
        "x": torch.randn(4, 6),
        "y": torch.randn(3, 2).to(torch.bfloat16),
        "z": torch.arange(4, dtype=torch.int32),
    }
    p = tmp_path / "model.safetensors"
    save_file(sd, str(p))
    got = load_state_dict_file(str(p))
    np.testing.assert_allclose(got["x"], sd["x"].numpy())
    np.testing.assert_allclose(got["y"].astype(np.float32),
                               sd["y"].float().numpy())
    np.testing.assert_array_equal(got["z"], sd["z"].numpy())


def test_load_hf_weights_from_local_dir(tmp_path, llama_oracle):
    """End-to-end: --weights_dir file -> converted tree (llama3_2 path,
    single safetensors file), without network."""
    from safetensors.torch import save_file

    from building_llm_from_scratch_tpu.weights import load_hf_weights

    save_file(llama_oracle.state_dict(), str(tmp_path / "model.safetensors"))
    params = load_hf_weights("llama3_2", "1B", LLAMA_TINY,
                             weights_dir=str(tmp_path))
    x = np.array([[3, 1, 4, 1, 5]], np.int32)
    with torch.no_grad():
        want = llama_oracle(torch.tensor(x, dtype=torch.long)).logits.numpy()
    got = np.asarray(forward(params, LLAMA_TINY, x))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)
